"""The N>=3 trunk-mesh kill/partition/heal soak (ISSUE 11 tentpole).

The ROADMAP's cluster story named its own acceptance test — "a 3-node
kill/partition/heal soak with zero qos1 loss and ledger-visible
degradation". Two variants live here:

- the FAST deterministic tier-1 variant: a full 3-node mesh (node A
  sharded, so trunk links provably SPREAD across shards — the round-15
  satellite) runs a scripted faultline schedule in-process: blackhole
  the A<->C link mid-qos1-stream, force ring_full on the sharded node,
  EIO node B's durable store, heal — asserting zero acked-QoS1 loss,
  every injected fault ledger-visible (faults.* stats + reason
  "fault"), and cross-node trace stitching (one sampled publish's
  timeline spans A's trunk_flush and C's trunk_recv);

- the SLOW soak (pytest.mark.slow): node B is a real SUBPROCESS killed
  with SIGKILL mid-stream (no goodbye), restarted, and resumed — its
  durable store replays every trunk-acked QoS1 message to the
  clean_start=false subscriber — while the A<->C link is blackholed
  mid-replay and healed. The at-least-once dup bound is asserted too.

Faultline site names exercised here (the nativecheck fault rule greps
for them): trunk_write, trunk_read, ring_seal, store_msync.
"""

import asyncio
import os
import signal
import socket
import subprocess
import sys
import time

import pytest

from emqx_tpu import native

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native lib unavailable")

from emqx_tpu.app import BrokerApp                              # noqa: E402
from emqx_tpu.broker.native_server import NativeBrokerServer    # noqa: E402
from emqx_tpu.mqtt.client import MqttClient                     # noqa: E402
from emqx_tpu.session.persistent import MemStore                # noqa: E402


def run(main):
    asyncio.run(main())


def _wait(pred, timeout=10.0, step=0.02):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if pred():
            return True
        time.sleep(step)
    return False


class _Mesh:
    """Three manually-wired native servers in a FULL trunk mesh (six
    directed links). Node A runs 2 shards so peer links land on
    different shards (peer ids 1 and 2 -> shards 1 and 0: the round-15
    link spread under test). The Python forward_fn oracle lane routes
    by destination node, modeling the cluster transport as reliable
    (store-and-forward is the cluster layer's own contract)."""

    NAMES = ("mA", "mB", "mC")

    def __init__(self, tmp_path, shards_a=2):
        self.apps = {}
        self.servers = {}
        for name in self.NAMES:
            app = BrokerApp(persistent_store=MemStore())
            app.broker.node = name
            self.apps[name] = app
        for name in self.NAMES:
            srv = NativeBrokerServer(
                port=0, app=self.apps[name], trunk_port=0,
                shards=shards_a if name == "mA" else 1,
                durable_dir=str(tmp_path / f"dur-{name}"),
                durable_fsync="batch",
                trace_sample_shift=0)
            self.servers[name] = srv

            def forward(dest, filt, msg, _self=self):
                dapp = _self.apps.get(dest)
                if dapp is None:
                    return
                deliveries = {}
                dapp.broker._dispatch_local(filt, msg, deliveries)
                dapp.cm.dispatch(deliveries)
            self.apps[name].broker.forward_fn = forward
        for srv in self.servers.values():
            srv.start()
            srv.set_trunk_ack_timeout(400)

    def wire(self):
        """Register every directed trunk link (the full mesh)."""
        for a in self.NAMES:
            for b in self.NAMES:
                if a != b:
                    self.servers[a].trunk_register(
                        b, "127.0.0.1", self.servers[b].trunk_port)
        for a in self.NAMES:
            assert _wait(lambda a=a: all(
                self.servers[a].trunk_peer_status().get(b)
                for b in self.NAMES if b != a), 15), (
                a, self.servers[a].trunk_peer_status())

    def peer_id(self, on, of):
        with self.servers[on]._mirror_lock:
            return self.servers[on]._trunk_peers[of]["id"]

    def stop(self):
        for srv in self.servers.values():
            srv.stop()


def test_three_node_mesh_fault_schedule_fast(tmp_path):
    """The tier-1 variant: mesh up (links spread across A's shards), a
    scripted blackhole -> ring_full -> store-EIO -> heal schedule with
    zero acked-QoS1 loss, ledger-visible chaos, and a cross-node
    stitched trace."""
    mesh = _Mesh(tmp_path)
    try:
        mesh.wire()
        A, B, C = (mesh.servers[n] for n in _Mesh.NAMES)

        # -- link spread (satellite): A's two peer links live on
        # different shards by peer-id modulo
        pid_b, pid_c = mesh.peer_id("mA", "mB"), mesh.peer_id("mA", "mC")
        assert pid_b % 2 != pid_c % 2, (pid_b, pid_c)

        got = {"b": [], "c": []}

        async def main():
            sub_b = MqttClient(port=B.port, clientid="msub-b")
            await sub_b.connect()
            await sub_b.subscribe("mesh/b", qos=1)
            sub_c = MqttClient(port=C.port, clientid="msub-c")
            await sub_c.connect()
            await sub_c.subscribe("mesh/c", qos=1)
            # a PERSISTENT subscriber on B: trunk-received publishes
            # persist in B's durable store (the store-EIO phase's prey)
            dur_b = MqttClient(port=B.port, clientid="mdur-b",
                               clean_start=False)
            await dur_b.connect()
            await dur_b.subscribe("mesh/b", qos=1)

            pub = MqttClient(port=A.port, clientid="mpub")
            await pub.connect()
            for topic, node in (("mesh/b", "mB"), ("mesh/c", "mC")):
                mesh.apps["mA"].broker.router.add_route(topic, node)
                await pub.publish(topic, b"warm", qos=1)
            for q in (sub_b, sub_c):
                m = await q.recv(timeout=10)
                assert m.payload == b"warm"
            await dur_b.recv(timeout=10)
            await asyncio.sleep(0.5)           # permits grant on idle

            async def drain(cli, key, n, timeout=20):
                deadline = time.monotonic() + timeout
                while (len([p for p in got[key] if p != b"warm"]) < n
                       and time.monotonic() < deadline):
                    try:
                        m = await cli.recv(timeout=2)
                    except asyncio.TimeoutError:
                        continue
                    got[key].append(m.payload)

            # -- healthy phase: both legs ride the trunk natively
            for i in range(6):
                await pub.publish("mesh/b", b"hb%02d" % i, qos=1)
                await pub.publish("mesh/c", b"hc%02d" % i, qos=1)
            await drain(sub_b, "b", 6)
            await drain(sub_c, "c", 6)
            assert _wait(lambda: A.fast_stats()["trunk_out"] >= 8), (
                A.fast_stats())
            # ...and on BOTH of A's shards (the spread, not a hotspot)
            per_shard = [s["trunk_batches_out"] for s in A.shard_stats()]
            assert all(n > 0 for n in per_shard), per_shard

            async def rewarm():
                # an UP event flushes A's permits (the punt->trunk
                # ordering guard): one sacrificial publish per topic
                # re-earns them so the next phase provably exercises
                # the NATIVE seams, not the Python fallback
                for t in ("mesh/b", "mesh/c"):
                    await pub.publish(t, b"warm", qos=1)
                await asyncio.sleep(0.6)

            # -- phase 1: BLACKHOLE the A->C link mid-stream
            A.fault_arm("trunk_write", "blackhole", key=pid_c)
            A.fault_arm("trunk_read", "blackhole", key=pid_c)
            for i in range(8):
                await pub.publish("mesh/c", b"pc%02d" % i, qos=1)
                await pub.publish("mesh/b", b"pb%02d" % i, qos=1)
            # B keeps flowing through the partition (mesh, not chain)
            await drain(sub_b, "b", 14)
            # the watchdog kills the silent link; A<->B stays up
            assert _wait(
                lambda: not A.trunk_peer_status().get("mC"), 12), (
                A.trunk_peer_status())
            assert A.trunk_peer_status().get("mB")
            # heal: redial + replay deliver every blackholed payload
            A.fault_disarm("trunk_write")
            A.fault_disarm("trunk_read")
            assert _wait(lambda: A.trunk_peer_status().get("mC"), 15)
            await drain(sub_c, "c", 14)

            # -- phase 2: forced ring_full on the sharded node — the
            # publish degrades through the REAL ladder and still lands
            # (one of the two trunk legs always crosses A's ring: the
            # peers live on DIFFERENT shards, the publisher on one)
            await rewarm()
            A.fault_arm("ring_seal", "full")
            for i in range(4):
                await pub.publish("mesh/b", b"rb%02d" % i, qos=1)
                await pub.publish("mesh/c", b"rc%02d" % i, qos=1)
            await drain(sub_b, "b", 18)
            await drain(sub_c, "c", 18)
            assert _wait(lambda: A.fault_fired("ring_seal") >= 1, 10), (
                A.fast_stats())
            A.fault_disarm("ring_seal")

            # -- phase 3: EIO node B's durable store under fsync=batch
            # (trunk-received publishes persist for the clean_start=
            # false subscriber; each batched append pays one msync)
            await rewarm()
            B.fault_arm("store_msync", "errno")
            for i in range(6):
                await pub.publish("mesh/b", b"sb%02d" % i, qos=1)
            await drain(sub_b, "b", 24)
            assert _wait(lambda: B.fault_fired("store_msync") >= 1, 10), (
                B.fast_stats())
            B.fault_disarm("store_msync")

            for c in (pub, sub_b, sub_c, dur_b):
                await c.close()

        run(main)

        # -- zero acked-QoS1 loss: every published payload delivered
        want_b = ({b"hb%02d" % i for i in range(6)}
                  | {b"pb%02d" % i for i in range(8)}
                  | {b"rb%02d" % i for i in range(4)}
                  | {b"sb%02d" % i for i in range(6)})
        want_c = ({b"hc%02d" % i for i in range(6)}
                  | {b"pc%02d" % i for i in range(8)}
                  | {b"rc%02d" % i for i in range(4)})
        assert want_b <= set(got["b"]), sorted(want_b - set(got["b"]))
        assert want_c <= set(got["c"]), sorted(want_c - set(got["c"]))

        # -- store-backed trunk ring (round 18): A's qos1 batches
        # journaled into its durable store alongside the memory ring,
        # and the peers' acks retired every record — the persisted
        # ring tracks the live one, never a grow-forever journal
        assert A.fast_stats()["trunk_ring_persisted"] >= 1, (
            A.fast_stats())
        assert _wait(
            lambda: A._durable_store.stats()["trunk_pending"] == 0), (
            A._durable_store.stats())

        # -- every injected fault is ledger-visible + counted
        assert A.fault_fired("trunk_write") >= 1
        assert A.fault_fired("ring_seal") >= 1
        assert B.fault_fired("store_msync") >= 1
        assert _wait(lambda: A.ledger.totals().get("fault", 0) >= 1)
        A._merge_fast_metrics()
        B._merge_fast_metrics()
        assert A.broker.metrics.val("faults.trunk_write") >= 1
        assert A.broker.metrics.val("faults.ring_seal") >= 1
        assert B.broker.metrics.val("faults.store_msync") >= 1
        assert any(e["reason"] == "fault" for e in B.ledger.recent())
        # organic degradation from the schedule shows up too
        led = A.ledger.totals()
        assert led.get("ring_full", 0) >= 1, led

        # -- cross-node trace stitching: one sampled publish's id has
        # trunk_flush on A and trunk_recv (or deliver_write) on B/C
        stitched = False
        for tid, spans in A.spans.recent(256):
            stages_a = {s[1] for s in spans}
            if "trunk_flush" not in stages_a:
                continue
            for other in (B, C):
                stages_o = {s[1] for s in other.spans.trace(tid)}
                if "trunk_recv" in stages_o or "deliver_write" in stages_o:
                    stitched = True
        assert stitched, (A.spans.recent(8), B.spans.recent(8))
    finally:
        mesh.stop()


# -- the slow soak: a REAL kill -9 in the schedule ----------------------------

_NODE_B_SRC = r"""
import sys, threading
sys.path.insert(0, %(repo)r)
from emqx_tpu.app import BrokerApp
from emqx_tpu.broker.native_server import NativeBrokerServer
from emqx_tpu.session.persistent import NativeDurableStore

# ONE recovery path (round 18): sessions, markers, messages AND the
# trunk replay ring recover from the same store walk after the kill
app = BrokerApp(persistent_store=NativeDurableStore(%(sess_dir)r))
app.broker.node = "soakB"
srv = NativeBrokerServer(port=%(port)d, app=app, trunk_port=%(trunk)d,
                         durable_fsync="batch")
srv.start()
if %(trunk_a)d:
    # B is also a trunk SENDER toward A: its outbound qos1 ring is the
    # store-backed leg the kill -9 must not lose
    app.broker.router.add_route("soak/a", "sA")
    srv.trunk_register("sA", "127.0.0.1", %(trunk_a)d)
print("READY", srv.port, srv.trunk_port, flush=True)
threading.Event().wait()          # run until killed
"""


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def _spawn_node_b(repo, port, trunk, sess_dir, trunk_a=0):
    src = _NODE_B_SRC % {"repo": repo, "port": port, "trunk": trunk,
                         "sess_dir": sess_dir, "trunk_a": trunk_a}
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.Popen([sys.executable, "-c", src],
                            stdout=subprocess.PIPE, text=True, env=env)
    line = proc.stdout.readline()
    assert line.startswith("READY"), line
    return proc


@pytest.mark.slow
def test_three_node_mesh_kill_partition_heal_soak(tmp_path):
    """The full acceptance soak: node B is a subprocess killed with
    SIGKILL mid-qos1-stream (its ONE durable store holds the session,
    the markers, the messages AND its outbound trunk replay ring), the
    A<->C link is blackholed mid-replay and healed, and node C's store
    takes an EIO burst — after heal: zero acked-QoS1 loss (every
    payload the publisher got a PUBACK for reaches its subscriber),
    at-least-once dup bounds honored, the chaos ledger-visible on
    every node.

    Round 18 extends the soak to the remaining two legs: B's
    subscriber stays CONNECTED through the kill (consume-on-ack keeps
    the marker of a written-but-unacked delivery, so resume
    retransmits — the closed PR-5 edge), and B is also a trunk SENDER
    toward A whose store-backed ring replays from recovered segments
    after the restart."""
    repo = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
    port_b, trunk_b = _free_port(), _free_port()
    sess_dir = str(tmp_path / "sessB")

    # nodes A and C in-process (A sharded: the spread rides the soak)
    apps = {}
    servers = {}
    pending_b = []    # the oracle lane's store-and-forward while B is dead
    for name in ("sA", "sC"):
        app = BrokerApp(persistent_store=MemStore())
        app.broker.node = name
        apps[name] = app
    for name in ("sA", "sC"):
        srv = NativeBrokerServer(
            port=0, app=apps[name], trunk_port=0,
            shards=2 if name == "sA" else 1,
            durable_dir=str(tmp_path / f"dur-{name}"),
            durable_fsync="batch")
        servers[name] = srv

        def forward(dest, filt, msg, _apps=apps):
            dapp = _apps.get(dest)
            if dapp is None:
                if dest == "soakB":
                    # B is remote (or dead): the cluster transport's
                    # store-and-forward contract, modeled by the test
                    pending_b.append((filt, msg))
                return
            deliveries = {}
            dapp.broker._dispatch_local(filt, msg, deliveries)
            dapp.cm.dispatch(deliveries)
        apps[name].broker.forward_fn = forward
        srv.start()
        srv.set_trunk_ack_timeout(500)
    A, C = servers["sA"], servers["sC"]

    trunk_a = A.trunk_port
    proc = _spawn_node_b(repo, port_b, trunk_b, sess_dir, trunk_a)
    got_b, got_c, got_a = [], [], []
    acked_b, acked_c, acked_a = [], [], []
    try:
        A.trunk_register("soakB", "127.0.0.1", trunk_b)
        A.trunk_register("sC", "127.0.0.1", C.trunk_port)
        assert _wait(lambda: A.trunk_peer_status().get("soakB"), 15)
        assert _wait(lambda: A.trunk_peer_status().get("sC"), 15)
        pid_c = None
        with A._mirror_lock:
            pid_c = A._trunk_peers["sC"]["id"]

        async def main():
            nonlocal proc
            # clean_start=false subscriber on B: its session and its
            # pending messages (B's ONE durable store) survive the kill
            sub_b = MqttClient(port=port_b, clientid="soaksub",
                               clean_start=False)
            await sub_b.connect()
            await sub_b.subscribe("soak/b", qos=1)
            # the trunk-sender leg (round 18): a subscriber on A for
            # the stream B publishes — B's outbound qos1 ring is
            # store-backed, so B's kill must not lose acked publishes
            sub_a = MqttClient(port=A.port, clientid="soaka")
            await sub_a.connect()
            await sub_a.subscribe("soak/a", qos=1)
            pub_b = MqttClient(port=port_b, clientid="soakbpub")
            await pub_b.connect()
            # persistent: trunk-received publishes persist in C's
            # durable store — the EIO phase's prey
            sub_c = MqttClient(port=C.port, clientid="soakc",
                               clean_start=False)
            await sub_c.connect()
            await sub_c.subscribe("soak/c", qos=1)

            pub = MqttClient(port=A.port, clientid="soakpub")
            await pub.connect()
            apps["sA"].broker.router.add_route("soak/b", "soakB")
            apps["sA"].broker.router.add_route("soak/c", "sC")

            relay_n = [0]

            async def relay_pending():
                # the oracle lane's store-and-forward: B is a separate
                # process, so A's PYTHON-lane legs for it (permit
                # windows + down windows) queue here and re-publish
                # into B whenever it is reachable
                if not pending_b:
                    return
                relay_n[0] += 1
                r = MqttClient(port=port_b,
                               clientid=f"soakrelay{relay_n[0]}")
                await r.connect()
                items = list(pending_b)
                pending_b.clear()
                for _filt, msg in items:
                    await r.publish(msg.topic, msg.payload, qos=1)
                await r.close()

            await pub.publish("soak/b", b"warm", qos=1)
            await pub.publish("soak/c", b"warm", qos=1)
            # B's sender leg warms its permit through B's python lane
            # (forward_fn-less: the warm publish is excluded from the
            # acked set) — later publishes ride B's trunk to A
            await pub_b.publish("soak/a", b"warm", qos=1)
            assert (await sub_c.recv(timeout=12)).payload == b"warm"
            await asyncio.sleep(0.7)

            async def pub_acked(topic, payload, sink):
                # qos1 publish() returns after PUBACK: every payload in
                # `sink` is an ACKED message the soak must not lose
                await pub.publish(topic, payload, qos=1)
                sink.append(payload)

            # healthy stream — drain the connected subscriber live
            for i in range(10):
                await pub_acked("soak/b", b"h%03d" % i, acked_b)
                await pub_acked("soak/c", b"g%03d" % i, acked_c)
            for i in range(6):
                # B PUBACKs only after the ring record journaled (the
                # FlushDirty ordering) — acked means replayable
                await pub_b.publish("soak/a", b"a%03d" % i, qos=1)
                acked_a.append(b"a%03d" % i)
            deadline = time.monotonic() + 25
            while (len([p for p in got_b if p != b"warm"]) < 10
                   and time.monotonic() < deadline):
                try:
                    m = await sub_b.recv(timeout=2)
                except asyncio.TimeoutError:
                    continue
                got_b.append(m.payload)

            # round 18: the subscriber STAYS CONNECTED through the
            # kill window. Consume-on-ack means a delivery written to
            # its socket but unacked at SIGKILL time keeps its store
            # marker, so the clean_start=false resume RETRANSMITS it —
            # the PR-5 edge ("written-but-unacked cannot retransmit"),
            # closed. Acked deliveries consumed their markers and are
            # already counted through the client's local queue below.

            # -- KILL -9 node B mid-stream (no goodbye): some of these
            # land durably in B (trunk-acked after fsync=batch), the
            # in-flight rest stays in A's replay ring; B's OWN sender
            # burst journals into its store-backed ring mid-flush
            for i in range(10, 16):
                await pub_acked("soak/b", b"h%03d" % i, acked_b)
            for i in range(6, 12):
                await pub_b.publish("soak/a", b"a%03d" % i, qos=1)
                acked_a.append(b"a%03d" % i)
            os.kill(proc.pid, signal.SIGKILL)
            proc.wait(timeout=10)
            # whatever B's subscriber already received (and auto-acked
            # — markers consumed) must count before the socket dies
            while True:
                try:
                    m = await sub_b.recv(timeout=1.0)
                except Exception:  # noqa: BLE001 — quiet or conn died
                    break
                got_b.append(m.payload)
            try:
                await sub_b.close()
            except Exception:  # noqa: BLE001 — socket died with B
                pass
            try:
                await pub_b.close()
            except Exception:  # noqa: BLE001
                pass
            assert _wait(
                lambda: not A.trunk_peer_status().get("soakB"), 15)
            # acked publishes keep flowing: the down window rides the
            # oracle lane's store-and-forward (pending_b)
            for i in range(16, 22):
                await pub_acked("soak/b", b"h%03d" % i, acked_b)

            # -- RESTART B; mid-replay, BLACKHOLE the A<->C link.
            # B's child re-registers its "sA" peer at boot: trunk_ident
            # merges the persisted ring from recovered segments and the
            # reconnect replays it into A (the sender leg's zero-loss)
            proc = _spawn_node_b(repo, port_b, trunk_b, sess_dir,
                                 trunk_a)
            A.fault_arm("trunk_write", "blackhole", key=pid_c)
            A.fault_arm("trunk_read", "blackhole", key=pid_c)
            for i in range(10, 18):
                await pub_acked("soak/c", b"g%03d" % i, acked_c)
            assert _wait(lambda: A.trunk_peer_status().get("soakB"),
                         20)
            # drain the oracle lane's store-and-forward into revived B
            await relay_pending()
            # the subscriber reconnects (clean_start=false) and drains
            # the durable-store replay + live traffic
            sub_b2 = MqttClient(port=port_b, clientid="soaksub",
                                clean_start=False)
            await sub_b2.connect()

            # -- HEAL the partition, then EIO C's durable store under
            # the restored native stream (the heal's UP event flushed
            # A's permits: one warm publish re-earns the trunk path so
            # C's store provably takes the batched appends)
            A.fault_disarm("trunk_write")
            A.fault_disarm("trunk_read")
            assert _wait(lambda: A.trunk_peer_status().get("sC"), 20)
            await pub.publish("soak/c", b"warm", qos=1)
            await asyncio.sleep(0.7)
            C.fault_arm("store_msync", "errno")
            for i in range(18, 24):
                await pub_acked("soak/c", b"g%03d" % i, acked_c)
            assert _wait(lambda: C.fault_fired("store_msync") >= 1, 15)
            C.fault_disarm("store_msync")

            # -- HEAL everything; drain both subscribers to the acked sets
            async def drain(cli, sink, want, timeout=40):
                deadline = time.monotonic() + timeout
                while (not want <= {p for p in sink}
                       and time.monotonic() < deadline):
                    try:
                        m = await cli.recv(timeout=2)
                    except asyncio.TimeoutError:
                        continue
                    if m.payload != b"warm":
                        sink.append(m.payload)

            # any Python-lane legs that queued during permit windows
            # while B was alive replay now too
            await relay_pending()
            await drain(sub_b2, got_b, set(acked_b))
            await drain(sub_c, got_c, set(acked_c))
            # the sender leg: B's recovered ring replayed into A
            await drain(sub_a, got_a, set(acked_a))
            for c in (pub, sub_b2, sub_c, sub_a):
                try:
                    await c.close()
                except (ConnectionError, OSError):
                    pass

        run(main)

        # -- ZERO acked-QoS1 loss: every PUBACK'd payload arrived
        assert set(acked_b) <= set(got_b), sorted(
            set(acked_b) - set(got_b))
        assert set(acked_c) <= set(got_c), sorted(
            set(acked_c) - set(got_c))
        # the store-backed trunk ring leg: B's acked publishes reached
        # A through live delivery or the post-restart segment replay
        assert set(acked_a) <= set(got_a), sorted(
            set(acked_a) - set(got_a))
        # -- at-least-once dup bound: replays may duplicate, but each
        # payload at most once per reconnect leg (generous bound: 4)
        for name, sink in (("b", got_b), ("c", got_c), ("a", got_a)):
            for p in set(sink):
                assert sink.count(p) <= 4, (name, p, sink.count(p))
        # -- chaos is ledger-visible on the injecting nodes
        assert A.fault_fired("trunk_write") >= 1
        assert C.fault_fired("store_msync") >= 1
        assert _wait(lambda: A.ledger.totals().get("fault", 0) >= 1)
        C._merge_fast_metrics()
        assert C.broker.metrics.val("faults.store_msync") >= 1
        assert any(e["reason"] == "fault" for e in C.ledger.recent())
    finally:
        try:
            proc.kill()
        except OSError:
            pass
        for srv in servers.values():
            srv.stop()
