"""Management REST API + CLI tests (reference ground:
apps/emqx_management/test/emqx_mgmt_api_*_SUITE.erl driven over HTTP)."""

import base64
import json
import urllib.error
import urllib.request

import pytest

from emqx_tpu.app import BrokerApp
from emqx_tpu.broker.channel import Channel
from emqx_tpu.config.config import Config
from emqx_tpu.mgmt.api import ManagementApi
from emqx_tpu.mgmt.cli import CtlClient, main as cli_main
from emqx_tpu.mqtt import packet as P


@pytest.fixture()
def api():
    conf = Config()
    conf.init_load("")
    app = BrokerApp.from_config(conf)
    mgmt = ManagementApi(app)
    mgmt.start(port=0)
    yield mgmt
    mgmt.stop()


def _token(mgmt) -> str:
    return _req(mgmt, "POST", "/api/v5/login",
                {"username": "admin", "password": "public"},
                auth=None)[1]["token"]


def _req(mgmt, method, path, body=None, auth="token", token=None):
    req = urllib.request.Request(
        f"http://127.0.0.1:{mgmt.port}{path}",
        data=json.dumps(body).encode() if body is not None else None,
        method=method)
    req.add_header("Content-Type", "application/json")
    if auth == "token":
        req.add_header("Authorization",
                       f"Bearer {token or _token(mgmt)}")
    try:
        with urllib.request.urlopen(req, timeout=5) as resp:
            raw = resp.read()
            return resp.status, (json.loads(raw) if raw else None)
    except urllib.error.HTTPError as e:
        raw = e.read()
        return e.code, (json.loads(raw) if raw else None)


def _mqtt_client(app, clientid):
    ch = Channel(app.broker, app.cm)
    ch.handle_in(P.Connect(proto_ver=P.MQTT_V5, clientid=clientid))
    return ch


def test_login_and_auth_required(api):
    code, err = _req(api, "GET", "/api/v5/status", auth=None)
    assert code == 401
    code, body = _req(api, "POST", "/api/v5/login",
                      {"username": "admin", "password": "wrong"},
                      auth=None)
    assert code == 401
    tok = _token(api)
    code, body = _req(api, "GET", "/api/v5/status", token=tok)
    assert code == 200 and body["status"] == "running"


def test_api_key_basic_auth(api):
    tok = _token(api)
    code, created = _req(api, "POST", "/api/v5/api_key", {}, token=tok)
    assert code == 201
    raw = f"{created['api_key']}:{created['api_secret']}".encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{api.port}/api/v5/metrics")
    req.add_header("Authorization",
                   "Basic " + base64.b64encode(raw).decode())
    with urllib.request.urlopen(req, timeout=5) as resp:
        assert resp.status == 200


def test_clients_subscriptions_topics_kick(api):
    app = api.app
    ch = _mqtt_client(app, "web1")
    ch.handle_in(P.Subscribe(packet_id=1,
                             topic_filters=[("t/#", {"qos": 1})]))
    tok = _token(api)
    code, page = _req(api, "GET", "/api/v5/clients", token=tok)
    assert code == 200 and page["meta"]["count"] == 1
    assert page["data"][0]["clientid"] == "web1"
    code, one = _req(api, "GET", "/api/v5/clients/web1", token=tok)
    assert one["subscriptions_cnt"] == 1
    code, subs = _req(api, "GET", "/api/v5/subscriptions", token=tok)
    assert subs["data"][0]["topic"] == "t/#"
    code, topics = _req(api, "GET", "/api/v5/topics", token=tok)
    assert any(t["topic"] == "t/#" for t in topics["data"])
    code, _ = _req(api, "DELETE", "/api/v5/clients/web1", token=tok)
    assert code == 204
    code, _ = _req(api, "GET", "/api/v5/clients/web1", token=tok)
    assert code == 404


def test_publish_endpoint_delivers(api):
    app = api.app
    ch = _mqtt_client(app, "watcher")
    ch.handle_in(P.Subscribe(packet_id=1,
                             topic_filters=[("cmd/#", {"qos": 0})]))
    code, body = _req(api, "POST", "/api/v5/publish",
                      {"topic": "cmd/go", "payload": "now", "qos": 0})
    assert code == 200 and "id" in body
    pubs = [p for p in ch.outbox if isinstance(p, P.Publish)]
    assert pubs and pubs[-1].payload == b"now"
    code, err = _req(api, "POST", "/api/v5/publish", {"payload": "x"})
    assert code == 400


def test_banned_endpoints(api):
    tok = _token(api)
    code, made = _req(api, "POST", "/api/v5/banned",
                      {"as": "clientid", "who": "evil"}, token=tok)
    assert code == 201
    code, page = _req(api, "GET", "/api/v5/banned", token=tok)
    assert page["meta"]["count"] == 1
    assert api.app.access.banned.check({"clientid": "evil"})
    code, _ = _req(api, "DELETE", "/api/v5/banned/clientid/evil",
                   token=tok)
    assert code == 204
    code, _ = _req(api, "DELETE", "/api/v5/banned/clientid/evil",
                   token=tok)
    assert code == 404


def test_config_endpoints(api):
    tok = _token(api)
    code, got = _req(api, "GET", "/api/v5/configs?path=mqtt.max_inflight",
                     token=tok)
    assert got["value"] == 32
    code, put = _req(api, "PUT", "/api/v5/configs",
                     {"path": "mqtt.max_inflight", "value": 64}, token=tok)
    assert code == 200 and put["value"] == 64
    code, err = _req(api, "PUT", "/api/v5/configs",
                     {"path": "mqtt.max_inflight", "value": "lots"},
                     token=tok)
    assert code == 400


def test_rules_crud_and_test(api):
    tok = _token(api)
    code, rule = _req(api, "POST", "/api/v5/rules", {
        "id": "r1", "sql": "SELECT * FROM 't/#'",
        "actions": [{"function": "console"}]}, token=tok)
    assert code == 201
    code, lst = _req(api, "GET", "/api/v5/rules", token=tok)
    assert lst["meta"]["count"] == 1
    code, upd = _req(api, "PUT", "/api/v5/rules/r1",
                     {"sql": "SELECT qos FROM 'u/#'"}, token=tok)
    assert code == 200 and upd["sql"] == "SELECT qos FROM 'u/#'"
    code, res = _req(api, "POST", "/api/v5/rule_test",
                     {"sql": "SELECT qos + 1 AS q FROM 't'",
                      "context": {"qos": 1}}, token=tok)
    assert res == [{"q": 2}]
    code, err = _req(api, "POST", "/api/v5/rules",
                     {"sql": "SELEC nope"}, token=tok)
    assert code == 400
    code, _ = _req(api, "DELETE", "/api/v5/rules/r1", token=tok)
    assert code == 204


def test_retainer_endpoints(api):
    app = api.app
    ch = _mqtt_client(app, "r1")
    ch.handle_in(P.Publish(topic="cfg/a", qos=0, retain=True,
                           payload=b"v1"))
    tok = _token(api)
    code, page = _req(api, "GET", "/api/v5/retainer/messages", token=tok)
    assert page["meta"]["count"] == 1
    assert base64.b64decode(page["data"][0]["payload"]) == b"v1"
    code, _ = _req(api, "DELETE", "/api/v5/retainer/message/cfg%2Fa",
                   token=tok)
    assert code == 204
    assert len(app.retainer) == 0


def test_metrics_stats_prometheus_alarms(api):
    _mqtt_client(api.app, "m1")
    tok = _token(api)
    code, metrics = _req(api, "GET", "/api/v5/metrics", token=tok)
    assert metrics["client.connected"] == 1
    code, stats = _req(api, "GET", "/api/v5/stats", token=tok)
    assert stats["connections.count"] == 1
    api.app.alarms.activate("test_alarm", {"x": 1}, "boom")
    code, alarms = _req(api, "GET", "/api/v5/alarms?activated=true",
                        token=tok)
    assert alarms[0]["name"] == "test_alarm"
    # prometheus is text
    req = urllib.request.Request(
        f"http://127.0.0.1:{api.port}/api/v5/prometheus")
    req.add_header("Authorization", f"Bearer {tok}")
    with urllib.request.urlopen(req, timeout=5) as resp:
        text = resp.read().decode()
    assert "emqx_client_connected" in text


def test_api_docs_public(api):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{api.port}/api-docs.json", timeout=5) as r:
        doc = json.loads(r.read())
    assert "/api/v5/clients" in doc["paths"]
    assert "get" in doc["paths"]["/api/v5/clients"]
    assert "mqtt" in doc["components"]["schemas"]["Config"]["properties"]


def test_cli_verbs(api, capsys):
    url = f"http://127.0.0.1:{api.port}"
    _mqtt_client(api.app, "cli1")
    assert cli_main(["--url", url, "status"]) == 0
    out = capsys.readouterr().out
    assert "running" in out
    assert cli_main(["--url", url, "clients", "list"]) == 0
    assert "cli1" in capsys.readouterr().out
    assert cli_main(["--url", url, "publish", "a/b", "hi", "--qos", "0"]
                    ) == 0
    capsys.readouterr()
    assert cli_main(["--url", url, "banned", "add", "clientid", "bad"]
                    ) == 0
    capsys.readouterr()
    assert cli_main(["--url", url, "banned", "list"]) == 0
    assert "bad" in capsys.readouterr().out
    assert cli_main(["--url", url, "clients", "kick", "cli1"]) == 0
    assert "kicked" in capsys.readouterr().out
    with pytest.raises(SystemExit):
        cli_main(["--url", url, "clients", "show", "ghost"])


def test_trace_and_slow_subs_endpoints(api):
    tok = _token(api)
    st, _ = _req(api, "POST", "/api/v5/trace",
                 {"name": "t1", "type": "clientid", "clientid": "dev-1"},
                 token=tok)
    assert st == 201
    _mqtt_client(api.app, "dev-1").handle_in(
        P.Publish(topic="a/b", payload=b"x", qos=0))
    st, data = _req(api, "GET", "/api/v5/trace", token=tok)
    assert st == 200 and data[0]["name"] == "t1" and data[0]["lines"] >= 1
    st, _ = _req(api, "PUT", "/api/v5/trace/t1/stop", token=tok)
    assert st == 200
    st, _ = _req(api, "DELETE", "/api/v5/trace/t1", token=tok)
    assert st == 204
    # slow subs
    api.app.slow_subs.record("c9", "t/9", 900)
    st, data = _req(api, "GET", "/api/v5/slow_subscriptions", token=tok)
    assert st == 200 and data["data"][0]["clientid"] == "c9"
    st, _ = _req(api, "DELETE", "/api/v5/slow_subscriptions", token=tok)
    assert st == 204


def test_mqtt_module_endpoints(api):
    tok = _token(api)
    st, _ = _req(api, "POST", "/api/v5/mqtt/topic_metrics",
                 {"topic": "m/+/x"}, token=tok)
    assert st == 201
    st, data = _req(api, "GET", "/api/v5/mqtt/topic_metrics", token=tok)
    assert st == 200 and data[0]["topic"] == "m/+/x"
    st, _ = _req(api, "DELETE", "/api/v5/mqtt/topic_metrics/m%2F%2B%2Fx",
                 token=tok)
    assert st == 204
    st, data = _req(api, "PUT", "/api/v5/mqtt/topic_rewrite",
                    [{"action": "publish", "source_topic": "a/#",
                      "re": "^a/(.+)$", "dest_topic": "b/$1"}], token=tok)
    assert st == 200 and len(data) == 1
    st, data = _req(api, "PUT", "/api/v5/mqtt/auto_subscribe",
                    [{"topic": "c/%c", "qos": 1}], token=tok)
    assert st == 200 and data[0]["topic"] == "c/%c"


def test_gateway_rest_surface(api):
    """emqx_gateway_api: list/detail/clients/kick/unload over REST."""
    import asyncio

    from emqx_tpu.gateway import stomp as ST

    async def main():
        gw = api.app.gateway.load(ST.StompGateway(port=0),
                                  {"mountpoint": "stomp/"})
        await gw.start_listeners()
        # a live stomp client session
        r, w = await asyncio.open_connection("127.0.0.1", gw.port)
        f = ST.Frame()
        w.write(f.serialize(ST.StompFrame(
            "CONNECT", {"accept-version": "1.2", "client-id": "gw-c1"})))
        await asyncio.wait_for(r.read(256), 5)

        st, gws = await asyncio.to_thread(_req, api, "GET", "/api/v5/gateways")
        assert st == 200
        (row,) = [g for g in gws["data"] if g["name"] == "stomp"]
        assert row["current_connections"] == 1
        assert row["mountpoint"] == "stomp/"

        st, one = await asyncio.to_thread(_req, api, "GET", "/api/v5/gateways/stomp")
        assert st == 200 and one["name"] == "stomp"
        st, _ = await asyncio.to_thread(_req, api, "GET", "/api/v5/gateways/nope")
        assert st == 404

        st, clients = await asyncio.to_thread(_req, api, "GET", "/api/v5/gateways/stomp/clients")
        assert st == 200
        assert clients["data"][0]["clientid"] == "gw-c1"

        st, _ = await asyncio.to_thread(_req, api, "DELETE",
                     "/api/v5/gateways/stomp/clients/gw-c1")
        assert st in (200, 204)
        st, clients = await asyncio.to_thread(_req, api, "GET", "/api/v5/gateways/stomp/clients")
        assert clients["data"] == []

        st, _ = await asyncio.to_thread(_req, api, "DELETE", "/api/v5/gateways/stomp")
        assert st in (200, 204)
        st, _ = await asyncio.to_thread(_req, api, "GET", "/api/v5/gateways/stomp")
        assert st == 404
        w.close()

    asyncio.run(main())


def test_cli_gateway_verbs(api, capsys):
    import asyncio

    from emqx_tpu.gateway import stomp as ST

    async def main():
        gw = api.app.gateway.load(ST.StompGateway(port=0))
        await gw.start_listeners()
        url = f"http://127.0.0.1:{api.port}"
        assert await asyncio.to_thread(
            cli_main, ["--url", url, "gateway", "list"]) == 0
        assert "stomp" in capsys.readouterr().out
        assert await asyncio.to_thread(
            cli_main, ["--url", url, "gateway", "show", "stomp"]) == 0
        assert await asyncio.to_thread(
            cli_main, ["--url", url, "gateway", "clients", "stomp"]) == 0
        assert await asyncio.to_thread(
            cli_main, ["--url", url, "gateway", "unload", "stomp"]) == 0
        assert api.app.gateway.get("stomp") is None

    asyncio.run(main())


def test_dashboard_page_served_and_escapes(api):
    """The built-in status page serves as explicit text/html (marker
    type, not body sniffing) and escapes interpolated values."""
    import urllib.request
    resp = urllib.request.urlopen(f"http://127.0.0.1:{api.port}/")
    assert resp.headers["Content-Type"].startswith("text/html")
    html = resp.read().decode()
    assert "broker status" in html
    # every dynamic interpolation routes through esc()
    assert "esc(c.clientid)" in html and "esc(v)" in html
    # plain-string handlers (prometheus) stay text/plain even though
    # a crafted metric label could start with a doctype
    tok = _token(api)
    req = urllib.request.Request(
        f"http://127.0.0.1:{api.port}/api/v5/prometheus",
        headers={"Authorization": f"Bearer {tok}"})
    assert urllib.request.urlopen(req).headers[
        "Content-Type"].startswith("text/plain")


def test_listeners_rest_surface(api):
    """emqx_mgmt_api_listeners: list the live listener set and stop one
    over REST (cross-thread onto the broker loop)."""
    import asyncio

    async def main():
        started = await api.app.listeners.start_all({
            "tcp_default": {"type": "tcp", "bind": "127.0.0.1:0"}})
        assert started == ["tcp:tcp_default"]
        st, rows = await asyncio.to_thread(
            _req, api, "GET", "/api/v5/listeners")
        assert st == 200
        (row,) = rows
        assert row["id"] == "tcp:tcp_default" and row["running"]
        st, _ = await asyncio.to_thread(
            _req, api, "DELETE", "/api/v5/listeners/tcp:tcp_default")
        assert st in (200, 204)
        st, rows = await asyncio.to_thread(
            _req, api, "GET", "/api/v5/listeners")
        assert rows == []
        st, _ = await asyncio.to_thread(
            _req, api, "DELETE", "/api/v5/listeners/tcp:tcp_default")
        assert st == 404

    asyncio.run(main())
