"""Resource manager FSM, buffer workers, connectors, bridges — mirrors
emqx_resource_SUITE / emqx_bridge_*_SUITE (with the memory connector in
the role of the demo connector, HTTP against a local stdlib server, and
the MQTT bridge looped back onto our own broker)."""

import asyncio
import http.server
import json
import threading

import pytest

from emqx_tpu.app import BrokerApp
from emqx_tpu.bridge.bridge import BridgeManager
from emqx_tpu.connector.http import HttpConnector
from emqx_tpu.connector.memory import MemoryConnector
from emqx_tpu.connector.mqtt import MqttConnector
from emqx_tpu.core.message import Message
from emqx_tpu.resource.resource import ResourceManager
from emqx_tpu.resource.worker import BufferWorker


# -- resource manager FSM ---------------------------------------------------

def test_manager_start_stop():
    c = MemoryConnector()
    m = ResourceManager("r1", c)
    assert m.start() and m.state == "connected" and c.started
    m.stop()
    assert m.state == "stopped" and not c.started


def test_manager_start_failure_then_retry():
    c = MemoryConnector()
    c.fail_start = True
    m = ResourceManager("r1", c, auto_restart_s=1.0)
    assert not m.start(now=0.0)
    assert m.state == "connecting" and m.error
    c.fail_start = False
    m.tick(now=0.5)                      # before backoff — still down
    assert m.state == "connecting"
    m.tick(now=1.5)
    assert m.state == "connected"


def test_health_check_flips_to_disconnected_and_recovers():
    c = MemoryConnector()
    m = ResourceManager("r1", c, auto_restart_s=1.0, health_check_s=1.0)
    m.start(now=0.0)
    c.healthy = False
    m.tick(now=1.5)                      # health probe fails
    assert m.state == "disconnected"
    c.healthy = True
    m.tick(now=3.0)                      # reconnect
    assert m.state == "connected"


# -- buffer worker ----------------------------------------------------------

def test_worker_batches_up_to_batch_size():
    c = MemoryConnector()
    m = ResourceManager("r1", c)
    m.start()
    w = BufferWorker(m, batch_size=3)
    for i in range(7):
        w.enqueue({"n": i})
    w.flush()
    assert [r["n"] for r in c.queries] == list(range(7))
    assert all(len(b) <= 3 for b in c.batches)
    assert len(c.batches[0]) == 3
    assert w.metrics["success"] == 7 and w.queuing() == 0


def test_worker_retries_while_down_then_delivers():
    c = MemoryConnector()
    m = ResourceManager("r1", c)
    m.start()
    c.fail_queries = True
    w = BufferWorker(m, batch_size=2, max_retries=10, retry_backoff_s=1.0)
    w.enqueue({"n": 1}, now=0.0)
    w.flush(now=0.0)
    assert w.queuing() == 1 and w.metrics["retried"] >= 1
    w.flush(now=0.5)                       # inside backoff — no attempt
    assert c.queries == []
    c.fail_queries = False
    w.flush(now=1.5)
    assert [r["n"] for r in c.queries] == [1]


def test_worker_drops_after_max_retries():
    c = MemoryConnector()
    m = ResourceManager("r1", c)
    m.start()
    c.fail_queries = True
    w = BufferWorker(m, max_retries=2, retry_backoff_s=0.0)
    w.enqueue({"n": 1}, now=0.0)
    for t in range(5):
        w.flush(now=float(t))
    assert w.queuing() == 0
    assert w.metrics["failed"] == 1


def test_worker_disk_queue_survives_restart(tmp_path):
    c = MemoryConnector()
    m = ResourceManager("r1", c)          # never started → queries queue up
    w = BufferWorker(m, queue_dir=str(tmp_path / "q"))
    w.enqueue({"n": 1})
    w.enqueue({"n": 2})
    # "restart": new worker over the same dir, resource now up
    m2 = ResourceManager("r1", c)
    m2.start()
    w2 = BufferWorker(m2, queue_dir=str(tmp_path / "q"))
    assert w2.queuing() == 2
    w2.flush()
    assert [r["n"] for r in c.queries] == [1, 2]


# -- http connector ---------------------------------------------------------

class _Recorder(http.server.BaseHTTPRequestHandler):
    received = []

    def do_POST(self):
        n = int(self.headers.get("Content-Length", 0))
        _Recorder.received.append(
            (self.path, self.rfile.read(n)))
        self.send_response(200)
        self.end_headers()
        self.wfile.write(b"ok")

    def log_message(self, *a):
        pass


@pytest.fixture
def http_server():
    _Recorder.received = []
    srv = http.server.HTTPServer(("127.0.0.1", 0), _Recorder)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield srv
    srv.shutdown()


def test_http_connector_round_trip(http_server):
    port = http_server.server_address[1]
    c = HttpConnector(f"http://127.0.0.1:{port}")
    m = ResourceManager("http1", c)
    assert m.start()
    res = m.query({"method": "post", "path": "/ingest", "body": "hello"})
    assert res["status"] == 200
    assert _Recorder.received == [("/ingest", b"hello")]


def test_http_bridge_renders_rule_columns(http_server):
    port = http_server.server_address[1]
    app = BrokerApp()
    bm = app.bridges
    bm.create("http", "sink", HttpConnector(f"http://127.0.0.1:{port}"),
              {"method": "post", "path": "/t/${topic}",
               "body": '{"p": "${payload}"}'})
    app.rules.create_rule(
        "r1", 'SELECT topic, payload FROM "sensors/#"',
        [{"function": "http:sink"}])
    app.broker.publish(Message(topic="sensors/a", payload=b"42"))
    bm.get("http:sink").worker.flush()
    assert _Recorder.received == [("/t/sensors/a", b'{"p": "42"}')]


def test_bridge_direct_egress_without_rule(http_server):
    port = http_server.server_address[1]
    app = BrokerApp()
    app.bridges.create(
        "http", "sink", HttpConnector(f"http://127.0.0.1:{port}"),
        {"method": "post", "path": "/direct", "body": "${payload}",
         "egress": {"local": {"topic": "out/#"}}})
    app.broker.publish(Message(topic="out/x", payload=b"D"))
    app.broker.publish(Message(topic="other", payload=b"N"))
    app.bridges.get("http:sink").worker.flush()
    assert _Recorder.received == [("/direct", b"D")]


def test_bridge_status_and_enable_disable():
    app = BrokerApp()
    c = MemoryConnector()
    app.bridges.create("mem", "m1", c, {})
    st = app.bridges.list()[0]
    assert st["id"] == "mem:m1" and st["resource"]["status"] == "connected"
    app.bridges.enable("mem:m1", False)
    assert app.bridges.get("mem:m1").manager.state == "stopped"
    assert not app.bridges.get("mem:m1").send({"x": 1})
    app.bridges.enable("mem:m1", True)
    assert app.bridges.get("mem:m1").manager.state == "connected"


def test_bridge_delete_detaches_all_traffic_sources():
    app = BrokerApp()
    c = MemoryConnector()
    app.bridges.create("mem", "m1", c,
                       {"egress": {"local": {"topic": "t/#"}}})
    app.rules.create_rule("r1", 'SELECT * FROM "t/#"',
                          [{"function": "mem:m1"}])
    b = app.bridges.get("mem:m1")
    app.broker.publish(Message(topic="t/x", payload=b"1"))
    assert b.worker.metrics["matched"] == 2     # rule action + direct hook
    assert app.bridges.delete("mem:m1")
    app.broker.publish(Message(topic="t/y", payload=b"2"))
    # nothing new reached the orphaned worker (action + hook removed)
    assert b.worker.metrics["matched"] == 2
    assert app.rules.metrics.get("r1", "actions.failed") == 1


# -- mqtt bridge over real sockets ------------------------------------------

def test_mqtt_bridge_egress_and_ingress_loopback():
    """Two brokers on one host: app A bridges egress to B and ingress
    from B — the emqx_connector_mqtt round trip."""
    from emqx_tpu.broker.server import BrokerServer
    from emqx_tpu.mqtt.client import MqttClient

    async def main():
        a, b = BrokerServer(port=0), BrokerServer(port=0)
        await a.start()
        await b.start()
        conn = MqttConnector(port=b.port, clientid="bridge-ab")
        # bridge setup blocks on the remote connect — run it off-loop
        # (in production the app tick drives this via to_thread too)
        bridge = await asyncio.to_thread(
            a.app.bridges.create,
            "mqtt", "tob", conn,
            {"egress": {"local": {"topic": "up/#"},
                        "remote": {"topic": "from_a/${topic}",
                                   "payload": "${payload}", "qos": 1}},
             "ingress": {"remote": {"topic": "down/#"},
                         "local": {"topic": "got/${topic}"}}},
        )
        # remote-side observer on B
        obs = MqttClient(port=b.port, clientid="obs")
        await obs.connect()
        await obs.subscribe("from_a/#", qos=1)
        # local subscriber on A for the ingress leg
        loc = MqttClient(port=a.port, clientid="loc")
        await loc.connect()
        await loc.subscribe("got/#", qos=0)

        # egress: publish on A under up/# → appears on B
        pub = MqttClient(port=a.port, clientid="p1")
        await pub.connect()
        await pub.publish("up/t1", b"hello-b", qos=1)
        await asyncio.to_thread(bridge.worker.flush)
        got = await obs.recv(timeout=5)
        assert got.topic == "from_a/up/t1" and got.payload == b"hello-b"

        # ingress: publish on B under down/# → reappears on A
        pubb = MqttClient(port=b.port, clientid="p2")
        await pubb.connect()
        await pubb.publish("down/t2", b"hello-a", qos=1)
        got2 = await loc.recv(timeout=5)
        assert got2.topic == "got/down/t2" and got2.payload == b"hello-a"

        for c in (obs, loc, pub, pubb):
            await c.close()
        conn.on_stop()
        await a.stop()
        await b.stop()

    asyncio.run(main())
