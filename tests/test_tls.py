"""TLS listeners (ssl/wss), mutual TLS, cert-derived identity, PSK gating,
and the config-driven listener supervisor — the esockd ssl/wss surface of
the reference (emqx_listeners.erl:196-238, apps/emqx_psk/)."""

import asyncio
import base64
import datetime
import os
import ssl

import pytest

from emqx_tpu.app import BrokerApp
from emqx_tpu.broker import tls
from emqx_tpu.broker.listeners import Listeners, build_listener, parse_bind
from emqx_tpu.broker.server import BrokerServer
from emqx_tpu.broker.ws import FrameDecoder, OP_BINARY, accept_key, encode_frame
from emqx_tpu.config.config import Config
from emqx_tpu.mqtt import packet as P
from emqx_tpu.mqtt.client import MqttClient
from emqx_tpu.mqtt.frame import Parser, serialize


# -- test PKI ------------------------------------------------------------------

@pytest.fixture(scope="module")
def pki(tmp_path_factory):
    """CA + server cert (CN=localhost, SAN 127.0.0.1) + client cert
    (CN=device-007), generated with `cryptography`."""
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.x509.oid import NameOID
    import ipaddress

    d = tmp_path_factory.mktemp("pki")
    now = datetime.datetime(2026, 1, 1)
    until = now + datetime.timedelta(days=3650)

    def keypair():
        return ec.generate_private_key(ec.SECP256R1())

    def write(name, key, cert):
        (d / f"{name}.key").write_bytes(key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.TraditionalOpenSSL,
            serialization.NoEncryption()))
        (d / f"{name}.pem").write_bytes(
            cert.public_bytes(serialization.Encoding.PEM))

    ca_key = keypair()
    ca_name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, "test-ca")])
    ca = (x509.CertificateBuilder()
          .subject_name(ca_name).issuer_name(ca_name)
          .public_key(ca_key.public_key())
          .serial_number(x509.random_serial_number())
          .not_valid_before(now).not_valid_after(until)
          .add_extension(x509.BasicConstraints(ca=True, path_length=1),
                         critical=True)
          .sign(ca_key, hashes.SHA256()))
    write("ca", ca_key, ca)

    def issue(name, cn, san=None):
        key = keypair()
        builder = (x509.CertificateBuilder()
                   .subject_name(x509.Name(
                       [x509.NameAttribute(NameOID.COMMON_NAME, cn),
                        x509.NameAttribute(NameOID.ORGANIZATION_NAME,
                                           "emqx-tpu-test")]))
                   .issuer_name(ca_name)
                   .public_key(key.public_key())
                   .serial_number(x509.random_serial_number())
                   .not_valid_before(now).not_valid_after(until))
        if san:
            builder = builder.add_extension(
                x509.SubjectAlternativeName(
                    [x509.DNSName("localhost"),
                     x509.IPAddress(ipaddress.ip_address("127.0.0.1"))]),
                critical=False)
        write(name, key, builder.sign(ca_key, hashes.SHA256()))

    issue("server", "localhost", san=True)
    issue("client", "device-007")
    return d


def server_opts(pki, **extra):
    return {"certfile": str(pki / "server.pem"),
            "keyfile": str(pki / "server.key"),
            "cacertfile": str(pki / "ca.pem"), **extra}


def client_opts(pki, with_cert=False):
    o = {"cacertfile": str(pki / "ca.pem")}
    if with_cert:
        o.update(certfile=str(pki / "client.pem"),
                 keyfile=str(pki / "client.key"))
    return o


async def tls_server(app=None, **kw):
    server = BrokerServer(port=0, app=app or BrokerApp(), **kw)
    await server.start()
    return server


# -- tcp+ssl -------------------------------------------------------------------

def test_tls_connect_pub_sub(pki):
    async def main():
        server = await tls_server(
            ssl_context=tls.make_server_context(server_opts(pki)))
        sub = MqttClient(port=server.port, clientid="s1", proto_ver=5,
                         ssl=tls.make_client_context(client_opts(pki)),
                         server_hostname="localhost")
        await sub.connect()
        await sub.subscribe("secure/+", qos=1)
        pub = MqttClient(port=server.port, clientid="p1", proto_ver=5,
                         ssl=tls.make_client_context(client_opts(pki)),
                         server_hostname="localhost")
        await pub.connect()
        await pub.publish("secure/x", b"over-tls", qos=1)
        msg = await asyncio.wait_for(sub.messages.get(), 5)
        assert (msg.topic, msg.payload) == ("secure/x", b"over-tls")
        await sub.disconnect(); await pub.disconnect(); await server.stop()
    asyncio.run(main())


def test_tls_refuses_untrusted_server_cert(pki, tmp_path):
    """A client pinning a different CA must fail the handshake."""
    async def main():
        server = await tls_server(
            ssl_context=tls.make_server_context(server_opts(pki)))
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)   # system CAs only
        c = MqttClient(port=server.port, ssl=ctx, server_hostname="localhost")
        with pytest.raises(ssl.SSLError):
            await c.connect()
        await server.stop()
    asyncio.run(main())


def test_mutual_tls_requires_client_cert(pki):
    async def main():
        server = await tls_server(
            ssl_context=tls.make_server_context(server_opts(
                pki, verify="verify_peer", fail_if_no_peer_cert=True)))
        # pin the no-cert probe to TLS 1.2: under 1.3 the client's
        # handshake "succeeds" locally and the certificate-required alert
        # only surfaces on first read; under 1.2 open_connection raises
        nocert = MqttClient(port=server.port, clientid="nc", proto_ver=5,
                            ssl=tls.make_client_context(
                                {**client_opts(pki),
                                 "versions": ["tlsv1.2"]}),
                            server_hostname="localhost")
        with pytest.raises((ssl.SSLError, ConnectionError)):
            await nocert.connect()
        ok = MqttClient(port=server.port, clientid="ok", proto_ver=5,
                        ssl=tls.make_client_context(
                            client_opts(pki, with_cert=True)),
                        server_hostname="localhost")
        await ok.connect()
        assert ok.connack.reason_code == 0
        await ok.disconnect(); await server.stop()
    asyncio.run(main())


def test_peer_cert_as_username(pki):
    """verify_peer + peer_cert_as_username=cn: the channel's effective
    username is the client cert CN, regardless of the CONNECT packet."""
    async def main():
        app = BrokerApp()
        server = await tls_server(
            app=app,
            ssl_context=tls.make_server_context(server_opts(
                pki, verify="verify_peer", fail_if_no_peer_cert=True)),
            peer_cert_as_username="cn")
        c = MqttClient(port=server.port, clientid="c7", proto_ver=5,
                       username="ignored", password=b"x",
                       ssl=tls.make_client_context(
                           client_opts(pki, with_cert=True)),
                       server_hostname="localhost")
        await c.connect()
        chan = app.cm.lookup_channel("c7")
        assert chan is not None
        assert chan.conninfo.username == "device-007"
        await c.disconnect(); await server.stop()
    asyncio.run(main())


def test_peer_cert_identity_fields():
    peercert = {"subject": ((("commonName", "device-007"),),
                            (("organizationName", "acme"),))}
    ident = tls.peer_cert_identity(peercert)
    assert ident["cn"] == "device-007"
    assert "CN=device-007" in ident["dn"] and "O=acme" in ident["dn"]
    assert tls.peer_cert_identity(None) == {}


# -- wss -----------------------------------------------------------------------

def test_wss_full_mqtt_flow(pki):
    from emqx_tpu.broker.ws import WsBrokerServer

    async def main():
        app = BrokerApp()
        server = WsBrokerServer(
            port=0, app=app,
            ssl_context=tls.make_server_context(server_opts(pki)))
        await server.start()
        r, w = await asyncio.open_connection(
            "127.0.0.1", server.port,
            ssl=tls.make_client_context(client_opts(pki)),
            server_hostname="localhost")
        key = base64.b64encode(os.urandom(16)).decode()
        w.write((f"GET /mqtt HTTP/1.1\r\nHost: localhost\r\n"
                 "Upgrade: websocket\r\nConnection: Upgrade\r\n"
                 f"Sec-WebSocket-Key: {key}\r\n"
                 "Sec-WebSocket-Version: 13\r\n"
                 "Sec-WebSocket-Protocol: mqtt\r\n\r\n").encode())
        resp = await r.readuntil(b"\r\n\r\n")
        assert b"101" in resp.split(b"\r\n")[0]
        assert accept_key(key).encode() in resp

        dec = FrameDecoder(require_mask=False)
        parser = Parser()
        w.write(encode_frame(OP_BINARY, serialize(
            P.Connect(proto_ver=P.MQTT_V4, clientid="wss1"), P.MQTT_V4),
            mask=True))
        await w.drain()
        pkts = []
        while not pkts:
            data = await asyncio.wait_for(r.read(4096), 5)
            for op, payload in dec.feed(data):
                if op == OP_BINARY:
                    pkts.extend(parser.feed(payload))
        assert pkts[0].type == P.CONNACK and pkts[0].reason_code == 0
        w.close()
        await server.stop()
    asyncio.run(main())


# -- TLS-PSK -------------------------------------------------------------------

def test_psk_gating_matches_runtime():
    """On runtimes without set_psk_server_callback (CPython < 3.13) the
    context builder must fail loudly at build time, not at handshake."""
    from emqx_tpu.access.psk import PskStore

    store = PskStore()
    store.insert("dev1", bytes.fromhex("deadbeef"))
    if tls.psk_supported():
        ctx = tls.make_server_context(
            {"ciphers": ["PSK-AES128-GCM-SHA256"],
             "versions": ["tlsv1.2"]}, psk_store=store)
        assert ctx is not None
    else:
        with pytest.raises(RuntimeError, match="3.13"):
            tls.make_server_context({}, psk_store=store)


@pytest.mark.skipif(not tls.psk_supported(),
                    reason="stdlib TLS-PSK callbacks need CPython >= 3.13")
def test_psk_handshake(pki):
    from emqx_tpu.access.psk import PskStore

    async def main():
        store = PskStore()
        store.insert("dev1", b"\x01" * 16)
        server = await tls_server(ssl_context=tls.make_server_context(
            {"ciphers": ["PSK-AES128-GCM-SHA256"], "versions": ["tlsv1.2"]},
            psk_store=store))
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_NONE
        ctx.minimum_version = ctx.maximum_version = ssl.TLSVersion.TLSv1_2
        ctx.set_ciphers("PSK-AES128-GCM-SHA256")
        ctx.set_psk_client_callback(lambda hint: ("dev1", b"\x01" * 16))
        c = MqttClient(port=server.port, clientid="pskc", ssl=ctx)
        await c.connect()
        assert c.connack.reason_code == 0
        await c.disconnect(); await server.stop()
    asyncio.run(main())


# -- config-driven listener supervisor ----------------------------------------

def test_parse_bind():
    assert parse_bind("0.0.0.0:1883") == ("0.0.0.0", 1883)
    assert parse_bind(":8883") == ("0.0.0.0", 8883)
    assert parse_bind("1883") == ("0.0.0.0", 1883)
    assert parse_bind(8080) == ("0.0.0.0", 8080)
    assert parse_bind("127.0.0.1:0") == ("127.0.0.1", 0)
    assert parse_bind("[::1]:8883") == ("::1", 8883)
    assert parse_bind("::1") == ("::1", 1883)
    assert parse_bind("broker.local") == ("broker.local", 1883)
    with pytest.raises(ValueError, match="invalid listener bind"):
        parse_bind("[::1]:port")


def test_bad_tls_version_is_a_config_error(pki):
    with pytest.raises(ValueError, match="unknown TLS version"):
        tls.make_server_context(server_opts(pki, versions=["tls1.2"]))


def test_listeners_from_config(pki):
    async def main():
        conf = Config()
        conf.init_load("""
        listeners {
          default { type = tcp, bind = "127.0.0.1:0" }
          secure {
            type = ssl, bind = "127.0.0.1:0"
            ssl_options {
              certfile = "%s", keyfile = "%s", cacertfile = "%s"
            }
          }
          websock { type = ws, bind = "127.0.0.1:0" }
          disabled_one { type = tcp, bind = "127.0.0.1:0", enabled = false }
        }
        """ % (pki / "server.pem", pki / "server.key", pki / "ca.pem"))
        app = BrokerApp.from_config(conf)
        sup = app.listeners
        started = await sup.start_all(conf.get("listeners"))
        assert sorted(started) == ["ssl:secure", "tcp:default", "ws:websock"]
        assert len(sup.info()) == 3

        tcp = sup.find("tcp:default")
        c1 = MqttClient(port=tcp.port, clientid="plain")
        await c1.connect()
        assert c1.connack.reason_code == 0

        sec = sup.find("ssl:secure")
        c2 = MqttClient(port=sec.port, clientid="tls",
                        ssl=tls.make_client_context(client_opts(pki)),
                        server_hostname="localhost")
        await c2.connect()
        assert c2.connack.reason_code == 0

        await c1.disconnect(); await c2.disconnect()
        assert await sup.stop("tcp:default")
        assert not await sup.stop("tcp:default")
        await sup.stop_all()
        assert sup.info() == []
    asyncio.run(main())


def test_quic_listener_slot_is_gated():
    app = BrokerApp()
    with pytest.raises(NotImplementedError, match="msquic"):
        build_listener(app, "q", {"type": "quic", "bind": "127.0.0.1:0"})


def test_noncontiguous_tls_versions_rejected(pki):
    with pytest.raises(ValueError, match="non-contiguous"):
        tls.make_server_context(
            server_opts(pki, versions=["tlsv1", "tlsv1.3"]))


def test_peer_cert_identity_requires_verify_peer(pki):
    app = BrokerApp()
    with pytest.raises(ValueError, match="verify_peer"):
        build_listener(app, "bad", {
            "type": "ssl", "bind": "127.0.0.1:0",
            "peer_cert_as_username": "cn",
            "ssl_options": server_opts(pki)})


def test_start_all_rolls_back_on_failure(pki):
    """A failing listener must unbind the ones already started so a
    retry doesn't hit EADDRINUSE."""
    async def main():
        app = BrokerApp()
        sup = Listeners(app)
        good = {"type": "tcp", "bind": "127.0.0.1:0"}
        bad = {"type": "ssl", "bind": "127.0.0.1:0",
               "ssl_options": {"certfile": "/nonexistent.pem"}}
        with pytest.raises(Exception):
            await sup.start_all({"a": good, "b": bad})
        assert sup.info() == []          # nothing left bound
        # retry with the bad listener fixed succeeds
        started = await sup.start_all({"a": good})
        assert started == ["tcp:a"]
        await sup.stop_all()
    asyncio.run(main())
