"""Native (C++) QoS2 fast path — round-5 stretch: exactly-once PUBLISH
handling below the GIL.

Reference semantics (emqx_session.erl:379-399 publish_in /
:478-492 pubrel_in; emqx_channel PUBREC/PUBREL/PUBCOMP exchange):
publisher-side dedup keys on the packet id while it awaits PUBREL;
subscriber-side delivery holds an inflight slot across
PUBLISH→PUBREC→PUBREL→PUBCOMP. The native plane owns a packet id's
exactly-once state iff the id is in ITS awaiting-rel set (publisher
side) or >= 32768 (broker-allocated delivery ids); everything else
forwards to the Python session, so the two planes can never
double-publish one id.
"""

import asyncio
import time

import pytest

from emqx_tpu import native

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native lib unavailable")

from emqx_tpu.app import BrokerApp            # noqa: E402
from emqx_tpu.broker.native_server import NativeBrokerServer  # noqa: E402
from emqx_tpu.mqtt import packet as P         # noqa: E402
from emqx_tpu.mqtt.client import MqttClient   # noqa: E402


def run(coro):
    asyncio.run(coro)


async def _settle(seconds=0.4):
    await asyncio.sleep(seconds)


async def _wait_stat(server, key, least=1, timeout=5.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if server.fast_stats()[key] >= least:
            return True
        await asyncio.sleep(0.05)
    return False


def test_qos2_end_to_end_native():
    """After the permit lands, a QoS2 publish runs the full
    PUBLISH→PUBREC→PUBREL→PUBCOMP exchange in C++ (fast_in advances)
    and the subscriber receives exactly once at qos2 with a native
    (>=32768) packet id."""
    server = NativeBrokerServer(port=0, app=BrokerApp())
    server.start()

    async def main():
        sub = MqttClient(port=server.port, clientid="q2s")
        await sub.connect()
        await sub.subscribe("q2/+", qos=2)
        pub = MqttClient(port=server.port, clientid="q2p")
        await pub.connect()
        for i in range(5):
            await pub.publish("q2/t", f"m{i}".encode(), qos=2)
            m = await sub.recv(timeout=10)
            assert m.payload == f"m{i}".encode()
            assert m.qos == 2
            await _settle(0.25)
        assert await _wait_stat(server, "fast_in", 1)
        # exactly once: nothing extra queued
        with pytest.raises(asyncio.TimeoutError):
            await sub.recv(timeout=0.5)
        await sub.close(); await pub.close()

    run(main())
    server.stop()


def test_qos2_dup_retransmit_is_deduped_natively():
    """A retransmitted PUBLISH (same pid, DUP set) while the first copy
    awaits PUBREL must NOT deliver again — the C++ awaiting-rel set is
    the dedup [MQTT-4.3.3]. The broker re-answers PUBREC; PUBREL then
    completes the exchange."""
    server = NativeBrokerServer(port=0, app=BrokerApp())
    server.start()

    async def main():
        sub = MqttClient(port=server.port, clientid="dds")
        await sub.connect()
        await sub.subscribe("dd2/t", qos=2)
        pub = MqttClient(port=server.port, clientid="ddp", auto_ack=False)
        await pub.connect()
        # earn the permit with a normal exchange
        await pub.publish("dd2/t", b"warm", qos=2)
        await sub.recv(timeout=10)
        await _settle(0.5)
        # manual exchange: PUBLISH, retransmit with DUP, then PUBREL
        pid = 77
        await pub._send(P.Publish(topic="dd2/t", payload=b"once", qos=2,
                                  packet_id=pid, properties={}))
        rec1 = await pub._expect(P.PUBREC, 10)
        assert rec1.packet_id == pid
        await pub._send(P.Publish(topic="dd2/t", payload=b"once", qos=2,
                                  packet_id=pid, dup=True, properties={}))
        rec2 = await pub._expect(P.PUBREC, 10)
        assert rec2.packet_id == pid
        await pub._send(P.PubRel(packet_id=pid))
        comp = await pub._expect(P.PUBCOMP, 10)
        assert comp.packet_id == pid
        # exactly one delivery despite two transmissions
        m = await sub.recv(timeout=10)
        assert m.payload == b"once"
        with pytest.raises(asyncio.TimeoutError):
            await sub.recv(timeout=0.5)
        # pid is released after PUBCOMP: reuse is a fresh publish
        await pub._send(P.Publish(topic="dd2/t", payload=b"again", qos=2,
                                  packet_id=pid, properties={}))
        await pub._expect(P.PUBREC, 10)
        await pub._send(P.PubRel(packet_id=pid))
        await pub._expect(P.PUBCOMP, 10)
        assert (await sub.recv(timeout=10)).payload == b"again"
        await sub.close(); await pub.close()

    run(main())
    server.stop()


def test_qos2_mixed_planes_share_pid_space_safely():
    """A publisher can interleave native (permitted) and Python
    (unpermitted: here a punt-marked topic) QoS2 publishes using
    arbitrary client pids: each plane completes only the exchanges it
    owns, nothing is lost, and nothing double-delivers."""
    server = NativeBrokerServer(port=0, app=BrokerApp())
    server.start()

    async def main():
        fastsub = MqttClient(port=server.port, clientid="mps")
        await fastsub.connect()
        await fastsub.subscribe("mp/fast", qos=2)
        # a persistent-session subscriber makes mp/slow punt-marked
        slowsub = MqttClient(port=server.port, clientid="mp-ps",
                             clean_start=False, proto_ver=5,
                             properties={"Session-Expiry-Interval": 60})
        await slowsub.connect()
        await slowsub.subscribe("mp/slow", qos=2)
        pub = MqttClient(port=server.port, clientid="mpp")
        await pub.connect()
        await pub.publish("mp/fast", b"w", qos=2)   # earn the permit
        await fastsub.recv(timeout=10)
        await _settle(0.5)
        for i in range(4):
            await pub.publish("mp/fast", f"f{i}".encode(), qos=2)
            await pub.publish("mp/slow", f"s{i}".encode(), qos=2)
        fgot = sorted([(await fastsub.recv(timeout=10)).payload
                       for _ in range(4)])
        sgot = sorted([(await slowsub.recv(timeout=10)).payload
                       for _ in range(4)])
        assert fgot == [b"f0", b"f1", b"f2", b"f3"], fgot
        assert sgot == [b"s0", b"s1", b"s2", b"s3"], sgot
        for s in (fastsub, slowsub):
            with pytest.raises(asyncio.TimeoutError):
                await s.recv(timeout=0.4)
        await fastsub.close(); await slowsub.close(); await pub.close()

    run(main())
    server.stop()


def test_qos2_subscriber_ack_phases_native():
    """Broker→subscriber QoS2: the delivery pid is native (>=32768),
    the broker answers the subscriber's PUBREC with PUBREL and frees
    the slot on PUBCOMP — all in C++ (native_acks advances while the
    Python session's inflight stays untouched)."""
    server = NativeBrokerServer(port=0, app=BrokerApp())
    server.start()

    async def main():
        sub = MqttClient(port=server.port, clientid="aps",
                         auto_ack=False)
        await sub.connect()
        await sub.subscribe("ap/t", qos=2)
        pub = MqttClient(port=server.port, clientid="app")
        await pub.connect()
        await pub.publish("ap/t", b"w", qos=2)
        m0 = await sub.recv(timeout=10)
        # manual subscriber-side exchange for the warm message
        if m0.qos == 2:
            await sub._send(P.PubRec(packet_id=m0.packet_id))
            rel = await sub._expect(P.PUBREL, 10)
            await sub._send(P.PubComp(packet_id=rel.packet_id))
        await _settle(0.5)
        await pub.publish("ap/t", b"native", qos=2)
        m = await sub.recv(timeout=10)
        assert m.payload == b"native" and m.qos == 2
        assert m.packet_id >= 32768, m.packet_id
        await sub._send(P.PubRec(packet_id=m.packet_id))
        rel = await sub._expect(P.PUBREL, 10)
        assert rel.packet_id == m.packet_id
        await sub._send(P.PubComp(packet_id=rel.packet_id))
        await _settle(0.3)
        assert server.fast_stats()["native_acks"] >= 1
        await sub.close(); await pub.close()

    run(main())
    server.stop()


def test_qos2_downgrade_to_subscriber_max():
    """min(publish qos, subscription qos): a qos1 subscriber of a
    native qos2 publish gets qos1 with a native pid; a qos0 subscriber
    gets qos0."""
    server = NativeBrokerServer(port=0, app=BrokerApp())
    server.start()

    async def main():
        s1 = MqttClient(port=server.port, clientid="dg1")
        await s1.connect()
        await s1.subscribe("dg/t", qos=1)
        s0 = MqttClient(port=server.port, clientid="dg0")
        await s0.connect()
        await s0.subscribe("dg/t", qos=0)
        pub = MqttClient(port=server.port, clientid="dgp")
        await pub.connect()
        for i in range(3):
            await pub.publish("dg/t", f"m{i}".encode(), qos=2)
            a = await s1.recv(timeout=10)
            b = await s0.recv(timeout=10)
            assert a.qos == 1 and a.payload == f"m{i}".encode()
            assert b.qos == 0 and b.payload == f"m{i}".encode()
            await _settle(0.2)
        assert await _wait_stat(server, "fast_in", 1)
        await s1.close(); await s0.close(); await pub.close()

    run(main())
    server.stop()
