"""Snappy codec: pure-Python vs native C++ differential tests, format
edge cases, and compressed Kafka record batches end-to-end through
MiniKafka (reference: snappyer NIF via wolff — SURVEY.md §2.4)."""

import random

import pytest

from emqx_tpu.connector.kafka import (CODEC_SNAPPY, KafkaClient, KafkaError,
                                      MiniKafka, decode_record_batch,
                                      encode_record_batch)
from emqx_tpu.utils.snappy import (SnappyError, compress, decompress,
                                   py_compress, py_decompress)


def _corpus():
    rng = random.Random(7)
    return [
        b"",
        b"a",
        b"abcd",
        b"hello hello hello hello hello",         # short repeats
        b"x" * 100_000,                            # long run (overlap copies)
        bytes(rng.randrange(256) for _ in range(5000)),   # incompressible
        b"".join(b"sensor/%d/temp=%d;" % (i % 40, i % 7)
                 for i in range(3000)),            # structured, compressible
        bytes(rng.randrange(4) for _ in range(70_000)),   # low entropy, big
    ]


def test_py_roundtrip():
    for data in _corpus():
        assert py_decompress(py_compress(data)) == data


def test_compression_actually_compresses():
    data = b"topic/device/telemetry " * 500
    out = py_compress(data)
    assert len(out) < len(data) // 4


def test_native_vs_python_differential():
    from emqx_tpu import native
    if not native.available():
        pytest.skip(f"native lib unavailable: {native.build_error()}")
    for data in _corpus():
        c_native = compress(data)
        # each implementation decodes the other's stream
        assert py_decompress(c_native) == data
        assert decompress(py_compress(data)) == data
        assert decompress(c_native) == data


def test_adversarial_far_matches_stay_in_bound():
    """4-byte matches at >=64KiB offsets would emit 5-byte copy4 tags
    (expansion) — the cost-effective-copy rule must keep the output
    within max_compressed so the native path cannot overflow its
    buffer."""
    rng = random.Random(3)
    # unique 4-byte blocks, then the same blocks again 70KB later:
    # every match is exactly 4 bytes at offset ~70000
    blocks = [bytes([rng.randrange(256) for _ in range(3)]) + b"\xaa"
              for _ in range(8000)]
    data = b"".join(blocks) + bytes(40_000) + b"".join(blocks)
    for codec in (py_compress, compress):
        out = codec(data)
        assert len(out) <= 32 + len(data) + len(data) // 6
        assert py_decompress(out) == data


def test_implausible_length_header_rejected_before_alloc():
    """A tiny stream claiming a 4 GiB uncompressed length must be
    rejected up front, not allocated."""
    huge = b"\xff\xff\xff\xff\x0f" + b"\x00a"   # varint ~4G, 1 literal
    with pytest.raises(SnappyError):
        decompress(huge)
    with pytest.raises(SnappyError):
        py_decompress(huge)


def test_malformed_streams_rejected():
    for bad in (b"", b"\xff\xff\xff\xff\xff\xff",   # unterminated varint
                b"\x05\x01",                        # copy before any output
                b"\x05\xfc" + b"x" * 3,             # literal past end
                b"\x02\x00a"):                      # length mismatch (says 2)
        with pytest.raises(SnappyError):
            py_decompress(bad)
        with pytest.raises(SnappyError):
            decompress(bad)


def test_record_batch_snappy_roundtrip():
    records = [(b"k%d" % i, b"payload-%d " % i * 20) for i in range(50)]
    batch = encode_record_batch(records, codec=CODEC_SNAPPY)
    plain = encode_record_batch(records)
    assert len(batch) < len(plain) // 2
    assert decode_record_batch(batch) == records
    with pytest.raises(KafkaError):
        encode_record_batch(records, codec=1)     # gzip unsupported


def test_produce_snappy_through_minikafka():
    srv = MiniKafka(topics={"zt": 1}).start()
    try:
        c = KafkaClient(port=srv.port, compression="snappy")
        offs = c.produce_many("zt", [(b"k", b"compressed " * 50)] * 3)
        assert offs == [0, 1, 2]
        assert [v for _k, v in srv.records[("zt", 0)]] == \
            [b"compressed " * 50] * 3
        c.close()
        with pytest.raises(KafkaError):
            KafkaClient(port=srv.port, compression="zstd")
    finally:
        srv.stop()
