"""Security layer tests: hashing, authn chain, authz sources, banned,
flapping, and end-to-end channel integration (the reference covers the
same ground in emqx_authn/emqx_authz suites + emqx_banned_SUITE)."""

import time

import pytest

from emqx_tpu.access.authn import (
    AuthnChain, BuiltinDbProvider, HttpProvider, JwtProvider,
    ScramProvider, jwt_sign,
)
from emqx_tpu.access.authz import (
    Authz, AuthzCache, BuiltinSource, ClientAclSource, FileSource,
    HttpAclSource, Rule,
)
from emqx_tpu.access.banned import Banned
from emqx_tpu.access.control import AccessControl
from emqx_tpu.access.flapping import Flapping
from emqx_tpu.access.hashing import (
    HashSpec, check_password, gen_salt, hash_password,
)


# -- hashing ---------------------------------------------------------------

@pytest.mark.parametrize("name", ["plain", "md5", "sha", "sha256", "sha512",
                                  "pbkdf2"])
def test_hash_roundtrip(name):
    spec = HashSpec(name=name)
    salt = gen_salt(spec)
    stored = hash_password(spec, salt, b"s3cret")
    assert check_password(spec, salt, stored, b"s3cret")
    assert not check_password(spec, salt, stored, b"wrong")


def test_salt_positions_differ():
    pw = b"pw"
    pre = HashSpec(name="sha256", salt_position="prefix")
    suf = HashSpec(name="sha256", salt_position="suffix")
    assert hash_password(pre, b"salt", pw) != hash_password(suf, b"salt", pw)


# -- authn -----------------------------------------------------------------

def test_empty_chain_is_anonymous_allow():
    assert AuthnChain().authenticate({"username": "x"})[0] == "ok"


def test_builtin_db_chain():
    db = BuiltinDbProvider()
    db.add_user("alice", "wonder", is_superuser=True)
    chain = AuthnChain([db])
    ok, extras = chain.authenticate(
        {"username": "alice", "password": b"wonder"})
    assert ok == "ok" and extras["is_superuser"]
    assert chain.authenticate(
        {"username": "alice", "password": b"nope"})[0] == "error"
    # unknown user: provider ignores; all-ignored chain denies
    assert chain.authenticate(
        {"username": "bob", "password": b"x"})[0] == "error"


def test_chain_fallthrough_order():
    db1 = BuiltinDbProvider()
    db2 = BuiltinDbProvider()
    db2.add_user("carol", "pw")
    chain = AuthnChain([db1, db2])
    assert chain.authenticate(
        {"username": "carol", "password": "pw"})[0] == "ok"


def test_jwt_provider():
    secret = b"topsecret"
    p = JwtProvider(secret)
    good = jwt_sign({"username": "dave", "exp": time.time() + 60,
                     "is_superuser": True,
                     "acl": {"pub": ["t/1"], "sub": ["t/#"]}}, secret)
    ret = p.authenticate({"username": "dave", "password": good})
    assert ret[0] == "ok"
    assert ret[1]["is_superuser"] and "acl" in ret[1]
    expired = jwt_sign({"exp": time.time() - 1}, secret)
    assert p.authenticate({"password": expired}) == ("error", "token_expired")
    forged = jwt_sign({"exp": time.time() + 60}, b"other")
    assert p.authenticate({"password": forged})[1] == "bad_token_signature"
    # non-JWT password → ignore so password providers can run after
    assert p.authenticate({"password": b"plain-pw"}) == "ignore"


def test_jwt_verify_claims_placeholder():
    secret = b"s"
    p = JwtProvider(secret, verify_claims={"sub": "${clientid}"})
    tok = jwt_sign({"sub": "c1", "exp": time.time() + 60}, secret)
    assert p.authenticate({"clientid": "c1", "password": tok})[0] == "ok"
    assert p.authenticate(
        {"clientid": "c2", "password": tok})[1] == "claim_mismatch"


def test_http_provider():
    calls = []

    def fake(body):
        calls.append(body)
        if body["username"] == "ok":
            return {"result": "allow", "is_superuser": True}
        if body["username"] == "no":
            return {"result": "deny"}
        return {"result": "ignore"}

    p = HttpProvider(fake)
    assert p.authenticate({"username": "ok", "password": b"x"})[0] == "ok"
    assert p.authenticate({"username": "no", "password": b"x"})[0] == "error"
    assert p.authenticate({"username": "??", "password": b"x"}) == "ignore"
    assert calls[0]["password"] == "x"


def test_scram_full_exchange():
    import base64
    import hashlib
    import hmac as hm

    p = ScramProvider(iterations=256)
    p.add_user("eve", "pw", is_superuser=True)
    cnonce = b"abc123"
    st, server_first = p.step("c1", b"n=eve,r=" + cnonce)
    assert st == "continue"
    fields = dict(kv.split(b"=", 1)
                  for kv in server_first.split(b",") if b"=" in kv)
    snonce, salt = fields[b"r"], base64.b64decode(fields[b"s"])
    iters = int(fields[b"i"])
    salted = hashlib.pbkdf2_hmac("sha256", b"pw", salt, iters)
    ckey = hm.new(salted, b"Client Key", hashlib.sha256).digest()
    stored = hashlib.sha256(ckey).digest()
    without_proof = b"c=biws,r=" + snonce
    auth_msg = (b"n=eve,r=" + cnonce + b"," + server_first + b","
                + without_proof)
    sig = hm.new(stored, auth_msg, hashlib.sha256).digest()
    proof = bytes(a ^ b for a, b in zip(ckey, sig))
    final = without_proof + b",p=" + base64.b64encode(proof)
    st, extras = p.step("c1", final)
    assert st == "ok" and extras["is_superuser"]
    assert extras["server_final"].startswith(b"v=")


# -- authz -----------------------------------------------------------------

def _ci(**kw):
    return {"clientid": "c1", "username": "u1",
            "peername": "10.1.2.3:5000", **kw}


def test_file_source_rules():
    src = FileSource.parse("""
        # dashboard user may watch $SYS
        allow  user=dashboard  subscribe  $SYS/#
        deny   all             subscribe  $SYS/#
        allow  clientid=c1     publish    t/${clientid}/up
        allow  ipaddr=10.0.0.0/8  all     local/#
        deny   all             all        #
    """)
    az = Authz([src], no_match="deny")
    assert az.authorize(_ci(username="dashboard"), "subscribe",
                        "$SYS/brokers") == "allow"
    assert az.authorize(_ci(), "subscribe", "$SYS/brokers") == "deny"
    assert az.authorize(_ci(), "publish", "t/c1/up") == "allow"
    assert az.authorize(_ci(), "publish", "t/c2/up") == "deny"
    assert az.authorize(_ci(), "subscribe", "local/x") == "allow"
    assert az.authorize(
        _ci(peername="192.168.0.9:1"), "subscribe", "local/x") == "deny"


def test_eq_topic_pins_literal():
    src = FileSource([Rule("allow", "all", "subscribe", ("eq t/+",))])
    az = Authz([src], no_match="deny")
    # 'eq' matches the literal '+' only, not the wildcard expansion
    assert az.authorize(_ci(), "subscribe", "t/+") == "allow"
    assert az.authorize(_ci(), "subscribe", "t/x") == "deny"


def test_builtin_source_precedence_and_no_match():
    src = BuiltinSource()
    src.set_rules(("clientid", "c1"),
                  [Rule("deny", "all", "publish", ("secret/#",))])
    src.set_rules("all", [Rule("allow", "all", "all", ("#",))])
    az = Authz([src], no_match="deny")
    assert az.authorize(_ci(), "publish", "secret/x") == "deny"
    assert az.authorize(_ci(), "publish", "open/x") == "allow"
    assert Authz([], no_match="allow").authorize(_ci(), "publish", "a") \
        == "allow"


def test_superuser_bypasses_sources():
    src = FileSource([Rule("deny", "all", "all", ("#",))])
    az = Authz([src])
    assert az.authorize(_ci(is_superuser=True), "publish", "x") == "allow"


def test_client_acl_source():
    src = ClientAclSource()
    ci = _ci(acl={"pub": ["up/${clientid}"], "sub": ["down/#"]})
    assert src.authorize(ci, "publish", "up/c1") == "allow"
    assert src.authorize(ci, "subscribe", "down/a/b") == "allow"
    assert src.authorize(ci, "publish", "other") == "deny"
    assert src.authorize(_ci(), "publish", "x") == "ignore"


def test_http_acl_source():
    src = HttpAclSource(lambda req: {"result": "deny"}
                        if req["topic"].startswith("adm/") else None)
    assert src.authorize(_ci(), "publish", "adm/x") == "deny"
    assert src.authorize(_ci(), "publish", "t/x") == "ignore"


def test_authz_cache_lru_ttl():
    c = AuthzCache(max_size=2, ttl_ms=10_000)
    c.put("publish", "a", "allow")
    c.put("publish", "b", "deny")
    assert c.get("publish", "a") == "allow"
    c.put("publish", "c", "allow")            # evicts LRU ("b")
    assert c.get("publish", "b") is None
    assert c.get("publish", "a") == "allow"
    c._d[("publish", "a")] = ("allow", time.time() - 11)
    assert c.get("publish", "a") is None      # TTL expired


# -- banned / flapping -----------------------------------------------------

def test_banned_check_and_expiry():
    b = Banned()
    b.create("clientid", "evil")
    b.create("peerhost", "9.9.9.9", duration_s=0.01)
    assert b.check({"clientid": "evil"})
    assert b.check({"clientid": "x", "peername": "9.9.9.9:123"})
    time.sleep(0.02)
    assert not b.check({"clientid": "x", "peername": "9.9.9.9:123"})
    assert b.check({"clientid": "evil"})      # no expiry → still banned
    b.delete("clientid", "evil")
    assert not b.check({"clientid": "evil"})


def test_flapping_trips_ban():
    b = Banned()
    f = Flapping(b, max_count=3, window_s=10, ban_duration_s=100)
    now = 1000.0
    assert not f.on_disconnect("c1", now)
    assert not f.on_disconnect("c1", now + 1)
    assert f.on_disconnect("c1", now + 2)
    assert b.check({"clientid": "c1"})
    # outside the window events don't count
    assert not f.on_disconnect("c2", now)
    assert not f.on_disconnect("c2", now + 20)
    assert not f.on_disconnect("c2", now + 40)


# -- channel integration ---------------------------------------------------

def _connect_app(app, clientid="c1", username=None, password=None):
    from emqx_tpu.broker.channel import Channel, ConnInfo
    from emqx_tpu.mqtt import packet as P

    ch = Channel(app.broker, app.cm,
                 conninfo=ConnInfo(peername="10.0.0.1:1234"))
    out = ch.handle_in(P.Connect(
        proto_ver=P.MQTT_V5, clientid=clientid, username=username,
        password=password, clean_start=True))
    return ch, out


def test_channel_authn_authz_end_to_end():
    from emqx_tpu.app import BrokerApp
    from emqx_tpu.mqtt import packet as P

    db = BuiltinDbProvider()
    db.add_user("alice", "pw")
    ac = AccessControl(
        authn=AuthnChain([db]),
        authz=Authz([FileSource.parse(
            "allow all publish t/#\ndeny all all #")], no_match="deny"),
    )
    app = BrokerApp(access_control=ac)

    # wrong password rejected at CONNECT
    _, out = _connect_app(app, username="alice", password=b"bad")
    assert out[0].reason_code == P.RC_BAD_USER_NAME_OR_PASSWORD

    ch, out = _connect_app(app, username="alice", password=b"pw")
    assert out[0].reason_code == P.RC_SUCCESS

    # authz: publish t/1 allowed, subscribe denied by the catch-all
    acks = ch.handle_in(P.Publish(topic="t/1", qos=1, packet_id=1,
                                  payload=b"x"))
    assert acks[0].reason_code == P.RC_SUCCESS
    suback = ch.handle_in(P.Subscribe(packet_id=2,
                                      topic_filters=[("t/#", {"qos": 0})]))
    assert suback[0].reason_codes == [P.RC_NOT_AUTHORIZED]


def test_channel_banned_at_connect():
    from emqx_tpu.app import BrokerApp
    from emqx_tpu.mqtt import packet as P

    app = BrokerApp()
    app.access.banned.create("clientid", "evil")
    _, out = _connect_app(app, clientid="evil")
    assert out[0].reason_code == P.RC_BANNED


def test_jwt_acl_enforced_via_channel():
    from emqx_tpu.app import BrokerApp
    from emqx_tpu.mqtt import packet as P

    secret = b"k"
    ac = AccessControl(authn=AuthnChain([JwtProvider(secret)]),
                       authz=Authz(no_match="deny"))
    app = BrokerApp(access_control=ac)
    tok = jwt_sign({"exp": time.time() + 60,
                    "acl": {"pub": ["up/${clientid}"]}}, secret)
    ch, out = _connect_app(app, clientid="dev7", password=tok)
    assert out[0].reason_code == P.RC_SUCCESS
    ok = ch.handle_in(P.Publish(topic="up/dev7", qos=1, packet_id=1,
                                payload=b""))
    assert ok[0].reason_code == P.RC_SUCCESS
    bad = ch.handle_in(P.Publish(topic="up/dev8", qos=1, packet_id=2,
                                 payload=b""))
    assert bad[0].reason_code == P.RC_NOT_AUTHORIZED


# -- JWT RS256 / JWKS (emqx_authn_jwt public-key + jwks flavors) ---------------

def _rsa_jwt(claims, kid="key-1"):
    """Mint an RS256 token + matching JWKS doc with `cryptography`.
    Callers skip cleanly when the optional dep is absent (the container
    ships without it; a ModuleNotFoundError here used to fail six tests
    instead of skipping them)."""
    import json as _json

    pytest.importorskip("cryptography")
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric import padding, rsa

    from emqx_tpu.access.authn import _b64url

    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    pub = key.public_key().public_numbers()

    def b64i(n, length=None):
        b = n.to_bytes((n.bit_length() + 7) // 8, "big")
        return _b64url(b).decode()

    header = {"alg": "RS256", "typ": "JWT", "kid": kid}
    signing = (_b64url(_json.dumps(header).encode()) + b"." +
               _b64url(_json.dumps(claims).encode()))
    sig = key.sign(signing, padding.PKCS1v15(), hashes.SHA256())
    token = (signing + b"." + _b64url(sig)).decode()
    jwks = {"keys": [{"kty": "RSA", "kid": kid,
                      "n": b64i(pub.n), "e": b64i(pub.e)}]}
    from cryptography.hazmat.primitives.serialization import (
        Encoding, PublicFormat)
    pem = key.public_key().public_bytes(
        Encoding.PEM, PublicFormat.SubjectPublicKeyInfo)
    return token, jwks, pem


def test_jwt_rs256_public_key_pem():
    import time as _t

    from emqx_tpu.access.authn import JwtProvider

    token, _jwks, pem = _rsa_jwt({"sub": "dev", "exp": _t.time() + 60,
                                  "is_superuser": True})
    p = JwtProvider(algorithm="RS256", public_key_pem=pem)
    result = p.authenticate({"password": token})
    assert result[0] == "ok" and result[1]["is_superuser"] is True
    # tampered payload (valid JSON, claim flipped) rejected
    import json as _json
    import time as _t

    from emqx_tpu.access.authn import _b64url
    head, _body, sig = token.split(".")
    forged = _b64url(_json.dumps(
        {"sub": "dev", "exp": _t.time() + 60,
         "is_superuser": False}).encode()).decode()
    assert p.authenticate(
        {"password": f"{head}.{forged}.{sig}"})[0] == "error"


def test_jwt_jwks_kid_selection_and_rotation():
    import time as _t

    from emqx_tpu.access.authn import JwtProvider

    token1, jwks1, _ = _rsa_jwt({"exp": _t.time() + 60}, kid="old")
    token2, jwks2, _ = _rsa_jwt({"exp": _t.time() + 60}, kid="new")
    docs = [jwks1, jwks2]
    fetches = []

    def jwks_fn():
        fetches.append(1)
        return docs[min(len(fetches) - 1, 1)]

    p = JwtProvider(algorithm="RS256", jwks_fn=jwks_fn)
    p.jwks_min_refresh_s = 0.0      # rotation without the test waiting out
    #                                 the production refresh throttle
    assert p.authenticate({"password": token1})[0] == "ok"
    # rotated key: kid 'new' is absent from the cached doc → one refresh
    assert p.authenticate({"password": token2})[0] == "ok"
    assert len(fetches) == 2


def test_jwt_es256():
    import json as _json
    import time as _t

    pytest.importorskip("cryptography")
    from cryptography.hazmat.primitives import hashes
    from cryptography.hazmat.primitives.asymmetric import ec
    from cryptography.hazmat.primitives.asymmetric.utils import (
        decode_dss_signature)

    from emqx_tpu.access.authn import JwtProvider, _b64url

    key = ec.generate_private_key(ec.SECP256R1())
    pub = key.public_key().public_numbers()
    header = {"alg": "ES256", "typ": "JWT"}
    claims = {"exp": _t.time() + 60}
    signing = (_b64url(_json.dumps(header).encode()) + b"." +
               _b64url(_json.dumps(claims).encode()))
    der = key.sign(signing, ec.ECDSA(hashes.SHA256()))
    r, s = decode_dss_signature(der)
    sig = r.to_bytes(32, "big") + s.to_bytes(32, "big")
    token = (signing + b"." + _b64url(sig)).decode()
    jwks = {"keys": [{"kty": "EC", "crv": "P-256",
                      "x": _b64url(pub.x.to_bytes(32, "big")).decode(),
                      "y": _b64url(pub.y.to_bytes(32, "big")).decode()}]}
    p = JwtProvider(algorithm="ES256", jwks=jwks)
    assert p.authenticate({"password": token})[0] == "ok"


def test_jwt_key_type_mismatch_is_an_error_not_a_crash():
    import time as _t

    from emqx_tpu.access.authn import JwtProvider

    token, _jwks, _pem = _rsa_jwt({"exp": _t.time() + 60})
    # EC-only JWKS against an RS256 token: must yield bad_token_signature
    ec_jwks = {"keys": [{"kty": "EC", "crv": "P-256",
                         "x": "AAAA", "y": "AAAA"}]}
    p = JwtProvider(algorithm="RS256", jwks=ec_jwks)
    assert p.authenticate({"password": token})[0] == "error"


def test_jwks_refresh_is_throttled():
    import time as _t

    from emqx_tpu.access.authn import JwtProvider

    token, _jwks, _pem = _rsa_jwt({"exp": _t.time() + 60})
    fetches = []

    def jwks_fn():
        fetches.append(1)
        return {"keys": []}              # never learns the key

    p = JwtProvider(algorithm="RS256", jwks_fn=jwks_fn)
    for _ in range(20):                  # bad-signature flood
        assert p.authenticate({"password": token})[0] == "error"
    assert len(fetches) <= 2, "refresh not throttled"


def test_jwt_empty_hs_secret_refused():
    from emqx_tpu.access.authn import JwtProvider

    with pytest.raises(ValueError, match="non-empty secret"):
        JwtProvider(secret=b"", algorithm="HS256")
    # asymmetric flavors don't need a secret
    JwtProvider(algorithm="RS256", jwks={"keys": []})


def test_jwt_factory_defaults_to_rs256_with_key_source():
    """{'mechanism': 'jwt', 'endpoint': ...} without an algorithm must
    NOT fall back to HS256-with-empty-secret (auth bypass)."""
    from emqx_tpu.app import BrokerApp
    from emqx_tpu.config.config import Config

    conf = Config()
    conf.init_load("")
    conf.put("authentication", [
        {"mechanism": "jwt", "endpoint": "http://127.0.0.1:9/jwks"},
    ], layer="local")
    app = BrokerApp.from_config(conf)
    (p,) = app.access.authn.providers
    assert p.algorithm == "RS256"
    # an attacker's HS256 token with the empty-secret HMAC is rejected
    forged = jwt_sign({"exp": time.time() + 60}, b"")
    assert p.authenticate({"password": forged})[0] == "error"


def test_jwks_dead_endpoint_fetches_are_throttled():
    """A JWKS endpoint that is DOWN from the start must not be re-fetched
    per token — the throttle applies to failures too."""
    from emqx_tpu.access.authn import JwtProvider

    fetches = []

    def broken():
        fetches.append(1)
        raise OSError("endpoint down")

    p = JwtProvider(algorithm="RS256", jwks_fn=broken)
    tok, _j, _pem = _rsa_jwt({"exp": time.time() + 60})
    for _ in range(20):
        assert p.authenticate({"password": tok})[0] == "error"
    assert len(fetches) <= 2, f"dead endpoint fetched {len(fetches)} times"
