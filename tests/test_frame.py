"""MQTT frame codec tests — mirrors apps/emqx/test/emqx_frame_SUITE.erl and
the parse∘serialize roundtrip property (apps/emqx/test/props/prop_emqx_frame.erl)."""

import random

import pytest

from emqx_tpu.mqtt import packet as P
from emqx_tpu.mqtt.frame import FrameError, Parser, serialize


def roundtrip(pkt, ver=P.MQTT_V4):
    data = serialize(pkt, ver)
    parser = Parser(version=ver)
    out = parser.feed(data)
    assert len(out) == 1, out
    return out[0]


def test_connect_roundtrip_v4():
    pkt = P.Connect(
        proto_ver=P.MQTT_V4, clean_start=True, keepalive=30,
        clientid="c1", username="u", password=b"p",
        will_flag=True, will_qos=1, will_retain=False,
        will_topic="will/t", will_payload=b"bye",
    )
    got = roundtrip(pkt)
    assert got == pkt


def test_connect_roundtrip_v5_properties():
    pkt = P.Connect(
        proto_ver=P.MQTT_V5, clientid="c2",
        properties={
            "Session-Expiry-Interval": 3600,
            "Receive-Maximum": 20,
            "User-Property": [("k", "v"), ("k", "v2")],
        },
        will_flag=True, will_topic="w", will_payload=b"",
        will_props={"Will-Delay-Interval": 5},
    )
    got = roundtrip(pkt, P.MQTT_V5)
    assert got == pkt


def test_publish_roundtrip():
    for ver in (P.MQTT_V4, P.MQTT_V5):
        pkt = P.Publish(topic="a/b", payload=b"\x00\xffhello", qos=1,
                        packet_id=7, retain=True, dup=True)
        assert roundtrip(pkt, ver) == pkt


def test_publish_v5_props():
    pkt = P.Publish(
        topic="t", payload=b"x", qos=2, packet_id=99,
        properties={
            "Topic-Alias": 3,
            "Message-Expiry-Interval": 60,
            "Subscription-Identifier": [1, 268435455],
            "Correlation-Data": b"\x01\x02",
            "Response-Topic": "r/t",
        },
    )
    assert roundtrip(pkt, P.MQTT_V5) == pkt


def test_qos3_rejected():
    data = serialize(P.Publish(topic="t", qos=2, packet_id=1))
    bad = bytes([data[0] | 0x06]) + data[1:]
    with pytest.raises(FrameError):
        Parser().feed(bad)


def test_acks_and_subs_roundtrip():
    assert roundtrip(P.PubAck(packet_id=5)) == P.PubAck(packet_id=5)
    v5ack = P.PubAck(packet_id=5, reason_code=P.RC_NO_MATCHING_SUBSCRIBERS)
    assert roundtrip(v5ack, P.MQTT_V5) == v5ack
    sub = P.Subscribe(packet_id=2, topic_filters=[
        ("a/+", {"qos": 1, "nl": 0, "rap": 0, "rh": 0}),
        ("b/#", {"qos": 2, "nl": 1, "rap": 1, "rh": 2}),
    ])
    assert roundtrip(sub, P.MQTT_V5) == sub
    assert roundtrip(P.SubAck(packet_id=2, reason_codes=[0, 1, 0x80])) == \
        P.SubAck(packet_id=2, reason_codes=[0, 1, 0x80])
    unsub = P.Unsubscribe(packet_id=3, topic_filters=["a/+", "b"])
    assert roundtrip(unsub) == unsub
    assert roundtrip(P.PingReq()) == P.PingReq()
    assert roundtrip(P.PingResp()) == P.PingResp()
    d5 = P.Disconnect(reason_code=P.RC_SESSION_TAKEN_OVER)
    assert roundtrip(d5, P.MQTT_V5) == d5
    auth = P.Auth(reason_code=0x18,
                  properties={"Authentication-Method": "SCRAM-SHA-1"})
    assert roundtrip(auth, P.MQTT_V5) == auth


def test_incremental_byte_by_byte():
    """The {active,N} contract: packets split at arbitrary boundaries."""
    pkts = [
        P.Connect(clientid="c"),
        P.Publish(topic="x/y", payload=b"z" * 300, qos=1, packet_id=1),
        P.PingReq(),
        P.Publish(topic="q", payload=b""),
    ]
    stream = b"".join(serialize(p) for p in pkts)
    parser = Parser()
    got = []
    for i in range(len(stream)):
        got.extend(parser.feed(stream[i : i + 1]))
    assert got == pkts
    # random chunking
    rng = random.Random(1)
    for _ in range(50):
        parser = Parser()
        got = []
        i = 0
        while i < len(stream):
            j = min(len(stream), i + rng.randint(1, 40))
            got.extend(parser.feed(stream[i:j]))
            i = j
        assert got == pkts


def test_remaining_length_bounds():
    # 4-byte varint max is valid framing; 5 bytes is malformed
    parser = Parser()
    with pytest.raises(FrameError):
        parser.feed(bytes([0x30, 0x80, 0x80, 0x80, 0x80, 0x01]))
    # max_size enforcement (emqx mqtt.max_packet_size analogue)
    parser = Parser(max_size=100)
    big = serialize(P.Publish(topic="t", payload=b"x" * 200))
    with pytest.raises(FrameError) as ei:
        parser.feed(big)
    assert ei.value.rc == P.RC_PACKET_TOO_LARGE


def test_malformed_utf8_and_truncation():
    pkt = serialize(P.Publish(topic="tt", payload=b"p"))
    # corrupt the topic bytes with invalid utf8
    bad = bytearray(pkt)
    bad[4:6] = b"\xff\xfe"
    with pytest.raises(FrameError):
        Parser().feed(bytes(bad))


def test_unknown_property_rejected():
    # property id 0x7f is not defined
    body = b"\x00\x01t" + bytes([2, 0x7F, 0x00]) + b"payload"
    frame = bytes([0x30]) + bytes([len(body)]) + body
    with pytest.raises(FrameError):
        Parser(version=P.MQTT_V5).feed(frame)


def test_randomized_roundtrip(rng):
    topics = ["a", "a/b", "x/+/y", "looooong/" * 10 + "end", "ü/码"]
    for _ in range(300):
        qos = rng.randrange(3)
        pkt = P.Publish(
            topic=rng.choice(topics),
            payload=bytes(rng.randrange(256) for _ in range(rng.randrange(0, 200))),
            qos=qos,
            retain=rng.random() < 0.5,
            dup=rng.random() < 0.5,
            packet_id=rng.randrange(1, 65536) if qos else None,
        )
        ver = rng.choice([P.MQTT_V4, P.MQTT_V5])
        assert roundtrip(pkt, ver) == pkt


def test_connect_reserved_flag():
    data = bytearray(serialize(P.Connect(clientid="c")))
    # connect flags byte: header(1) + len(1) + "MQTT"(6) + ver(1) = offset 9
    data[9] |= 0x01
    with pytest.raises(FrameError):
        Parser().feed(bytes(data))
