"""The native (C++) CoAP gateway plane — coap.h/host.cc driven against
gateway/coap.py as the protocol oracle: every test client speaks the
ORACLE's codec over real UDP sockets, so any disagreement between the
two RFC 7252 implementations fails here, and one shared vector set
locks the codecs together byte-for-byte (the sn.h discipline).

Covers: the shared codec vectors (parse+serialize parity incl. the
malformed set), /ps publish + observe end-to-end on the native plane,
observe-notify parity BIT-IDENTICAL to the asyncio gateway across
TCP/WS/SN/CoAP cross-protocol fan-out, the MID-dedup window (replay,
in-flight drop, and the parity-audited counter-wrap eviction), CON
retransmit timing on the timer wheel vs the oracle's backoff, the
retransmit-exhaustion give-up (observer dropped, ledger-visible), the
fast-path permit ride with punts==0, block-wise + props fallback to
the Python oracle (never a partial exchange), the plain-GET retained
read, qos1 publishes gated on the native ack plane, re-register under
a new clientid, faultline coverage of the conn_read/conn_write seams,
the LwM2M register/observe flows over the native CoAP transport, and
the asyncio-gateway deployment fallback."""

import socket
import time

import pytest

from emqx_tpu import native
from emqx_tpu.gateway import coap as C

pytestmark = pytest.mark.skipif(
    not native.available(), reason=f"native lib: {native.build_error()}")


@pytest.fixture()
def app():
    from emqx_tpu.app import BrokerApp

    return BrokerApp()


@pytest.fixture()
def server(app):
    from emqx_tpu.broker.native_server import NativeBrokerServer

    srv = NativeBrokerServer(
        port=0, app=app, coap_port=0, sn_port=0, ws_port=0,
        session_opts={"max_inflight": 32})
    srv.start()
    yield srv
    srv.stop()


class CoapSock:
    """Blocking UDP client speaking the ORACLE's codec (C.Frame)."""

    def __init__(self, port: int):
        self.f = C.Frame()
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.sock.settimeout(5)
        self.sock.connect(("127.0.0.1", port))
        self._mid = 0

    def next_mid(self) -> int:
        self._mid = self._mid % 0xFFFF + 1
        return self._mid

    def send(self, m: C.CoapMessage) -> None:
        self.sock.send(self.f.serialize(m))

    def send_raw(self, data: bytes) -> None:
        self.sock.send(data)

    def recv(self, timeout: float = 5.0) -> C.CoapMessage:
        self.sock.settimeout(timeout)
        data = self.sock.recv(65536)
        msgs, _ = self.f.parse(data, None)
        assert msgs, f"unparseable datagram {data!r}"
        return msgs[0]

    def recv_raw(self, timeout: float = 5.0) -> bytes:
        self.sock.settimeout(timeout)
        return self.sock.recv(65536)

    def request(self, code, path, payload=b"", token=b"t", options=(),
                queries=(), con=True, mid=None):
        opts = list(options) + C.uri_path_opts(path)
        for q in queries:
            opts.append((C.OPT_URI_QUERY, q.encode()))
        m = C.CoapMessage(C.CON if con else C.NON, code,
                          mid if mid is not None else self.next_mid(),
                          token, opts, payload)
        self.send(m)
        return m

    def observe(self, topic, token=b"obs", cid="c-obs", qos=0):
        qs = [f"clientid={cid}"]
        if qos:
            qs.append(f"qos={qos}")
        self.request(C.GET, f"ps/{topic}", token=token,
                     options=[(C.OPT_OBSERVE, b"")], queries=qs)
        ack = self.recv()
        assert ack.code == C.CONTENT, hex(ack.code)
        return ack

    def close(self):
        self.sock.close()


# ---------------------------------------------------------------------------
# shared codec vectors: the oracle codec and coap.h must agree byte-level
# ---------------------------------------------------------------------------

def _vectors() -> list:
    return [
        C.CoapMessage(C.CON, C.GET, 1, b"", C.uri_path_opts("ps/a/b")),
        C.CoapMessage(C.CON, C.POST, 0xFFFF, b"tok12345",
                      C.uri_path_opts("ps/t")
                      + [(C.OPT_URI_QUERY, b"qos=1"),
                         (C.OPT_URI_QUERY, b"clientid=dev-1")],
                      b"payload"),
        C.CoapMessage(C.NON, C.PUT, 7, b"t",
                      C.uri_path_opts("ps/x") + [(C.OPT_OBSERVE, b"")],
                      b""),
        C.CoapMessage(C.ACK, C.CONTENT, 42, b"obs1",
                      [(C.OPT_OBSERVE, b"\x00\x00\x01")], b"21"),
        C.CoapMessage(C.ACK, C.CHANGED, 43, b"tk"),
        C.CoapMessage(C.RST, C.EMPTY, 44, b""),
        C.CoapMessage(C.CON, C.EMPTY, 45, b""),          # CoAP ping
        # 13/14 delta+length extensions, out-of-order options (the
        # serializer's stable sort), empty option values
        C.CoapMessage(C.CON, C.GET, 46, b"zz",
                      [(2000, b"x" * 300), (C.OPT_URI_PATH, b"ps"),
                       (C.OPT_URI_PATH, b"t"), (C.OPT_ETAG, b"\x01")]),
        C.CoapMessage(C.CON, C.POST, 47, b"",
                      C.uri_path_opts("ps/t")
                      + [(C.OPT_BLOCK1, b"\x0a"),
                         (C.OPT_SIZE1, b"\x01\x00")], b"chunk"),
        C.CoapMessage(C.NON, C.CONTENT, 0, b"\x00" * 8,
                      [(C.OPT_OBSERVE, b"\xff\xff\xff")], b"\xff\x00"),
    ]


def test_codec_vectors_shared():
    """Every vector's oracle parse→reserialize must equal the native
    codec's parse→reserialize of the SAME datagram — the lock that
    keeps the two RFC 7252 implementations from drifting apart."""
    f = C.Frame()
    for m in _vectors():
        wire = f.serialize(m)
        parsed, _ = f.parse(wire, None)
        assert len(parsed) == 1, m
        oracle_bytes = f.serialize(parsed[0])
        n, native_bytes = native.coap_roundtrip(wire)
        assert n == 1, m
        assert native_bytes == oracle_bytes, (
            f"codec drift on {m}: native={native_bytes!r} "
            f"oracle={oracle_bytes!r}")


def test_codec_malformed_drops_both_planes():
    """The malformed set yields ZERO messages on both planes: short
    headers, bad version, tkl > 8, truncated 13/14 extension bytes."""
    f = C.Frame()
    bad = [b"", b"\x40", b"\x40\x01\x00",        # short header
           b"\x80\x01\x00\x01",                  # version 2
           b"\x49\x01\x00\x01" + b"t" * 9,       # tkl 9
           b"\x40\x01\x00\x01\xd1",              # 13-ext delta cut off
           b"\x40\x01\x00\x01\xe1\x00"]          # 14-ext needs 2 bytes
    for w in bad:
        try:
            pkts, _ = f.parse(w, None)
        except Exception:
            pkts = []  # the oracle raises mid-parse; its UDP listener
            #            drops the datagram — the same observable outcome
        n, out = native.coap_roundtrip(w)
        assert pkts == [] and n == 0 and out == b"", w


def test_codec_clamped_option_value_parity():
    """An option whose declared length overruns the datagram yields a
    clamped short value on BOTH planes (Python slice semantics)."""
    f = C.Frame()
    # delta 11 (uri-path), len 8, but only 3 value bytes present
    w = b"\x40\x01\x00\x01\xb8abc"
    pkts, _ = f.parse(w, None)
    assert pkts[0].opt(C.OPT_URI_PATH) == b"abc"
    n, out = native.coap_roundtrip(w)
    assert n == 1 and out == f.serialize(pkts[0])


# ---------------------------------------------------------------------------
# native gateway end-to-end
# ---------------------------------------------------------------------------

def test_publish_observe_e2e(server):
    sub = CoapSock(server.coap_port)
    ack = sub.observe("room/t", token=b"obs1", cid="c-sub")
    assert ack.opt(C.OPT_OBSERVE) == (1).to_bytes(3, "big")
    assert ack.token == b"obs1"

    pub = CoapSock(server.coap_port)
    pub.request(C.PUT, "ps/room/t", payload=b"21",
                queries=["clientid=c-pub"])
    pack = pub.recv()
    assert pack.code == C.CHANGED
    note = sub.recv()
    assert note.type == C.NON and note.code == C.CONTENT
    assert note.payload == b"21" and note.token == b"obs1"
    assert note.opt(C.OPT_OBSERVE) == (2).to_bytes(3, "big")
    # unobserve: no further notifications
    sub.request(C.GET, "ps/room/t", token=b"obs1",
                options=[(C.OPT_OBSERVE, (1).to_bytes(1, "big"))],
                queries=["clientid=c-sub"])
    assert sub.recv().code == C.CONTENT
    pub.request(C.PUT, "ps/room/t", payload=b"22",
                queries=["clientid=c-pub"])
    assert pub.recv().code == C.CHANGED
    with pytest.raises(socket.timeout):
        sub.recv(timeout=0.6)
    sub.close()
    pub.close()


def test_coap_ping_answers_rst(server):
    c = CoapSock(server.coap_port)
    c.send(C.CoapMessage(C.CON, C.EMPTY, 99, b""))
    pong = c.recv()
    assert pong.type == C.RST and pong.code == C.EMPTY and pong.mid == 99
    assert server.host.stats()["coap_pings"] >= 1
    c.close()


def test_mid_dedup_replays_cached_response(server, app):
    seen = []
    app.hooks.add("message.publish",
                  lambda m: seen.append(bytes(m.payload)) or None,
                  priority=-500)
    c = CoapSock(server.coap_port)
    req = c.request(C.POST, "ps/dup/t", payload=b"once",
                    queries=["clientid=c-dup"], mid=77)
    first = c.recv_raw()
    # byte-identical retransmission: replayed response, NOT re-executed
    c.send_raw(c.f.serialize(req))
    second = c.recv_raw(timeout=5)
    assert second == first
    deadline = time.time() + 2
    while time.time() < deadline and seen.count(b"once") < 1:
        time.sleep(0.05)
    assert seen.count(b"once") == 1
    assert server.host.stats()["coap_dedup_hits"] >= 1
    c.close()


def test_mid_dedup_wrap_evicts_on_new_token(server):
    """The parity-audited wrap bug: a recycled mid under a DIFFERENT
    token is a NEW exchange, not a retransmission — an observer sees
    BOTH publishes (a message-publish hook would go blind the moment
    the topic earns its fast-path permit)."""
    sub = CoapSock(server.coap_port)
    sub.observe("wrap/t", token=b"wsub", cid="c-wsub")
    c = CoapSock(server.coap_port)
    c.request(C.POST, "ps/wrap/t", payload=b"one", token=b"tk1",
              queries=["clientid=c-wrap"], mid=5)
    assert c.recv().code == C.CHANGED
    c.request(C.POST, "ps/wrap/t", payload=b"two", token=b"tk2",
              queries=["clientid=c-wrap"], mid=5)
    assert c.recv().code == C.CHANGED
    assert sub.recv().payload == b"one"
    assert sub.recv().payload == b"two"
    c.close()
    sub.close()


def test_oracle_tm_dedup_token_wrap_unit():
    """The oracle TransportManager's own wrap fix (no server)."""
    clock = [0.0]
    tm = C.TransportManager(now_fn=lambda: clock[0])
    m1 = C.CoapMessage(C.CON, C.POST, 9, b"tk1")
    tm.remember(m1, ["resp1"])
    assert tm.dedup(m1) == ["resp1"]
    m2 = C.CoapMessage(C.CON, C.POST, 9, b"tk2")  # recycled mid
    assert tm.dedup(m2) is None                   # evicted, fresh
    assert tm.dedup(m1) is None                   # old entry gone


def test_observe_seq_rollover_oracle_unit(app):
    """The parity-audited 2^24 rollover: per-observer seq wraps instead
    of crashing in to_bytes(3)."""
    from emqx_tpu.gateway.ctx import GwContext

    class Msg:
        def __init__(self, topic, payload):
            self.topic, self.payload = topic, payload

    ch = C.Channel(GwContext(app, "coap"))
    ch.clientid = "c-roll"
    ch.observers["t"] = [b"tok", 0, 0xFFFFFE]
    out = ch.handle_deliver([("t", Msg("t", b"a")), ("t", Msg("t", b"b")),
                             ("t", Msg("t", b"c"))])
    seqs = [int.from_bytes(m.opt(C.OPT_OBSERVE), "big") for m in out]
    assert seqs == [0xFFFFFF, 0, 1]


def test_con_retransmit_timing_on_wheel_vs_oracle(server, app):
    """A qos1 observer's CON notify retransmits on the wheel with the
    oracle's exponential shape (base, 2x, 4x...), resent byte-VERBATIM;
    exhaustion drops the observer (RFC 7641 §4.5), frees the window
    slot, and lands in the degradation ledger as coap_giveup."""
    server.host.set_coap_ack_timeout(150)
    time.sleep(0.3)  # ops apply on the next poll cycle
    try:
        sub = CoapSock(server.coap_port)
        sub.observe("rex/t", token=b"rex", cid="c-rex", qos=1)
        pub = CoapSock(server.coap_port)
        pub.request(C.PUT, "ps/rex/t", payload=b"x",
                    queries=["clientid=c-rexp"])
        assert pub.recv().code == C.CHANGED
        # first transmission + kMaxRetransmit verbatim retransmissions
        first = sub.recv_raw()
        stamps = [time.monotonic()]
        copies = [first]
        for _ in range(4):
            copies.append(sub.recv_raw(timeout=6))
            stamps.append(time.monotonic())
        assert all(cp == first for cp in copies[1:])
        gaps = [stamps[i + 1] - stamps[i] for i in range(4)]
        # exponential shape: each gap roughly doubles (wheel ticks and
        # poll cadence blur the edges; the RATIO is the contract)
        for a, b in zip(gaps, gaps[1:]):
            assert b > a * 1.3, gaps
        # give-up: no more copies, observer dropped, ledger-visible
        with pytest.raises(socket.timeout):
            sub.recv(timeout=3.0)
        st = server.host.stats()
        assert st["coap_rexmits"] >= 4
        assert st["coap_giveups"] == 1
        deadline = time.time() + 3
        m = app.broker.metrics
        while (time.time() < deadline
               and m.val("messages.ledger.coap_giveup") < 1):
            time.sleep(0.05)
        assert m.val("messages.ledger.coap_giveup") >= 1
        # the observation is gone: a new publish draws no notify
        pub.request(C.PUT, "ps/rex/t", payload=b"y",
                    queries=["clientid=c-rexp"])
        assert pub.recv().code == C.CHANGED
        with pytest.raises(socket.timeout):
            sub.recv(timeout=0.8)
        sub.close()
        pub.close()
    finally:
        server.host.set_coap_ack_timeout(0)


def test_con_notify_ack_frees_ack_plane_slot(server):
    """ACKing a CON notify settles it (no retransmit) and frees the
    native window slot via the synthesized PUBACK."""
    server.host.set_coap_ack_timeout(200)
    time.sleep(0.3)
    try:
        sub = CoapSock(server.coap_port)
        sub.observe("ackf/t", token=b"af", cid="c-ackf", qos=1)
        pub = CoapSock(server.coap_port)
        for i in range(3):
            pub.request(C.PUT, "ps/ackf/t", payload=b"m%d" % i,
                        queries=["clientid=c-afp"])
            assert pub.recv().code == C.CHANGED
            note = sub.recv()
            assert note.type == C.CON and note.payload == b"m%d" % i
            sub.send(C.CoapMessage(C.ACK, C.EMPTY, note.mid, b""))
        time.sleep(0.6)  # past the base timeout: nothing retransmits
        with pytest.raises(socket.timeout):
            sub.recv(timeout=0.3)
        assert server.host.stats()["coap_rexmits"] == 0
        sub.close()
        pub.close()
    finally:
        server.host.set_coap_ack_timeout(0)


def test_rst_on_notify_cancels_observation(server):
    sub = CoapSock(server.coap_port)
    sub.observe("rstc/t", token=b"rc", cid="c-rst")
    pub = CoapSock(server.coap_port)
    pub.request(C.PUT, "ps/rstc/t", payload=b"a",
                queries=["clientid=c-rstp"])
    assert pub.recv().code == C.CHANGED
    note = sub.recv()
    assert note.payload == b"a"
    # RFC 7641 §3.6: RST cancels the observation for ANY notify type
    sub.send(C.CoapMessage(C.RST, C.EMPTY, note.mid, b""))
    time.sleep(0.3)
    pub.request(C.PUT, "ps/rstc/t", payload=b"b",
                queries=["clientid=c-rstp"])
    assert pub.recv().code == C.CHANGED
    with pytest.raises(socket.timeout):
        sub.recv(timeout=0.8)
    sub.close()
    pub.close()


def test_fast_path_ride_with_punts_zero(server):
    """After the permit grant, CoAP publishes ride the native fast
    path: the blast adds ZERO punts and the observer sees every
    message in order."""
    sub = CoapSock(server.coap_port)
    sub.observe("fast/t", token=b"fp", cid="c-fsub")
    pub = CoapSock(server.coap_port)
    pub.request(C.PUT, "ps/fast/t", payload=b"warm",
                queries=["clientid=c-fpub"])
    assert pub.recv().code == C.CHANGED
    assert sub.recv().payload == b"warm"
    time.sleep(1.0)  # the permit-grant settle
    before = server.host.stats()
    n = 200
    got = []
    for i in range(n):
        pub.request(C.PUT, "ps/fast/t", payload=b"%04d" % i, con=False,
                    queries=["clientid=c-fpub"])
        # lockstep drain: UDP offers no backpressure, and the point is
        # the plane, not the burst rate
        got.append(sub.recv().payload)
    after = server.host.stats()
    assert got == [b"%04d" % i for i in range(n)]
    assert after["punts"] == before["punts"], "fast-path publishes punted"
    assert after["coap_in"] - before["coap_in"] == n
    assert after["fast_in"] - before["fast_in"] == n
    sub.close()
    pub.close()


def test_qos1_publish_ack_gated_on_ack_plane(server, app):
    """A CON ?qos=1 publish answers 2.04 exactly once, only after the
    MQTT ack lands (broker-side accounting proves the qos1 ingest)."""
    c = CoapSock(server.coap_port)
    c.request(C.POST, "ps/q1/t", payload=b"v", token=b"q1",
              queries=["clientid=c-q1", "qos=1"])
    ack = c.recv()
    assert ack.code == C.CHANGED and ack.token == b"q1"
    with pytest.raises(socket.timeout):
        c.recv(timeout=0.4)   # exactly once
    c.close()


def test_plain_get_retained_native(server, app):
    from emqx_tpu.core.message import Message

    app.retainer.store(Message(topic="ret/t", payload=b"body",
                               flags={"retain": True}))
    time.sleep(0.3)  # mirror op applies on the next poll cycle
    c = CoapSock(server.coap_port)
    before = server.host.stats()["coap_punts"]
    c.request(C.GET, "ps/ret/t", queries=["clientid=c-get"])
    r = c.recv()
    assert r.code == C.CONTENT and r.payload == b"body"
    c.request(C.GET, "ps/ret/missing", queries=["clientid=c-get"])
    assert c.recv().code == C.NOT_FOUND
    assert server.host.stats()["coap_punts"] == before, \
        "plain GETs must serve natively from the snapshot"
    c.close()


def test_props_retained_fallback_to_oracle(server, app):
    """A props-carrying retained message makes the mirror incomplete:
    plain GETs degrade WHOLE to the Python oracle — and still answer
    correctly (never a partial set)."""
    from emqx_tpu.core.message import Message

    app.retainer.store(Message(
        topic="pr/t", payload=b"withprops", flags={"retain": True},
        headers={"properties": {"user_property": [("k", "v")]}}))
    time.sleep(0.3)
    c = CoapSock(server.coap_port)
    before = server.host.stats()["coap_punts"]
    c.request(C.GET, "ps/pr/t", queries=["clientid=c-pr"])
    r = c.recv()
    assert r.code == C.CONTENT and r.payload == b"withprops"
    assert server.host.stats()["coap_punts"] > before
    c.close()


def test_blockwise_upload_falls_back_whole(server, app):
    """A Block1 upload degrades the WHOLE exchange to the oracle: the
    blocks reassemble there and publish once, through the same broker
    the native plane serves."""
    seen = []
    app.hooks.add("message.publish",
                  lambda m: seen.append(bytes(m.payload)) or None,
                  priority=-500)
    c = CoapSock(server.coap_port)
    chunks = [b"A" * 64, b"B" * 64, b"C" * 10]
    for i, chunk in enumerate(chunks):
        more = 1 if i < len(chunks) - 1 else 0
        c.request(C.POST, "ps/blk/t", payload=chunk,
                  options=[(C.OPT_BLOCK1, C.encode_block(i, more, 64))],
                  queries=["clientid=c-blk"])
        r = c.recv()
        assert r.code == (C.CONTINUE_231 if more else C.CHANGED), hex(r.code)
    deadline = time.time() + 3
    while time.time() < deadline and not seen:
        time.sleep(0.05)
    assert seen == [b"".join(chunks)]
    assert server.host.stats()["coap_punts"] >= len(chunks)
    c.close()


def test_block2_download_served_by_oracle(server, app):
    """A retained body past the block2 threshold punts to the oracle's
    stateless slicing (ETag + Block2 + Size2)."""
    from emqx_tpu.core.message import Message

    body = bytes(range(256)) * 10          # 2560B > the 1024 threshold
    app.retainer.store(Message(topic="big/t", payload=body,
                               flags={"retain": True}))
    time.sleep(0.3)
    c = CoapSock(server.coap_port)
    got = bytearray()
    num = 0
    while True:
        c.request(C.GET, "ps/big/t",
                  options=[(C.OPT_BLOCK2, C.encode_block(num, 0, 512))],
                  queries=["clientid=c-big"])
        r = c.recv()
        assert r.code == C.CONTENT
        got += r.payload
        _, more, _ = C.parse_block(r.opt(C.OPT_BLOCK2))
        if not more:
            break
        num += 1
    assert bytes(got) == body
    assert server.host.stats()["coap_punts"] >= 1
    c.close()


def test_reregister_new_clientid_drops_old_observers(server, app):
    """A request carrying a NEW ?clientid= re-registers the endpoint:
    old observers are dropped (their tokens never leak into the new
    session) and the new identity is re-authenticated — the oracle
    parity-audit fix, native edition."""
    c = CoapSock(server.coap_port)
    c.observe("rr/t", token=b"old", cid="c-old")
    pub = CoapSock(server.coap_port)
    pub.request(C.PUT, "ps/rr/t", payload=b"one",
                queries=["clientid=c-rrp"])
    assert pub.recv().code == C.CHANGED
    assert c.recv().payload == b"one"
    # same endpoint re-registers as a different device
    c.request(C.POST, "ps/other/t", payload=b"hello",
              queries=["clientid=c-new"])
    assert c.recv().code == C.CHANGED
    time.sleep(0.3)
    pub.request(C.PUT, "ps/rr/t", payload=b"two",
                queries=["clientid=c-rrp"])
    assert pub.recv().code == C.CHANGED
    with pytest.raises(socket.timeout):
        c.recv(timeout=0.8)    # the old observation died with c-old
    c.close()
    pub.close()


def test_oracle_reregister_unit(app):
    """The oracle Channel's own re-register fix (no server): observers
    and sessions reset when the clientid changes."""
    from emqx_tpu.gateway.ctx import GwContext

    ctx = GwContext(app, "coap")
    ch = C.Channel(ctx)
    out = ch.handle_in(C.CoapMessage(
        C.CON, C.GET, 1, b"tk",
        C.uri_path_opts("ps/t") + [(C.OPT_OBSERVE, b""),
                                   (C.OPT_URI_QUERY, b"clientid=c1")]))
    assert out[0].code == C.CONTENT and "t" in ch.observers
    assert ch.clientid == "c1"
    out = ch.handle_in(C.CoapMessage(
        C.CON, C.POST, 2, b"tk",
        C.uri_path_opts("ps/t") + [(C.OPT_URI_QUERY, b"clientid=c2")],
        b"x"))
    assert out[0].code == C.CHANGED
    assert ch.clientid == "c2" and ch.observers == {}


# ---------------------------------------------------------------------------
# observe-notify parity: bit-identical to the asyncio oracle across
# TCP/WS/SN/CoAP cross-protocol fan-out (the acceptance gate)
# ---------------------------------------------------------------------------

def test_observe_notify_parity_bit_identical_cross_protocol(server, app):
    """One CoAP observer on the NATIVE plane, fed by publishers on all
    four transports (TCP, WS, SN, CoAP) in strict order; the SAME
    observer registration + payload sequence driven through the asyncio
    gateway must yield BYTE-IDENTICAL datagrams — registration ACK and
    every notification (mids, tokens, per-observer sequence numbers,
    payloads)."""
    import asyncio
    import base64 as b64
    import os as _os
    import threading

    from emqx_tpu.broker.ws import (OP_BINARY, FrameDecoder, encode_frame)
    from emqx_tpu.core.message import Message
    from emqx_tpu.gateway import mqttsn as SN
    from emqx_tpu.mqtt import packet as P
    from emqx_tpu.mqtt.frame import Parser, serialize

    payloads = [b"from-tcp", b"from-ws", b"from-sn", b"from-coap"]
    reg = C.CoapMessage(
        C.CON, C.GET, 1, b"xp",
        C.uri_path_opts("ps/xp/t") + [(C.OPT_OBSERVE, b""),
                                      (C.OPT_URI_QUERY,
                                       b"clientid=c-xp")])
    reg_wire = C.Frame().serialize(reg)

    # -- native arm: the observer on the C++ plane, one publisher per
    # transport, lockstep so ordering is strict
    sub = CoapSock(server.coap_port)
    sub.send_raw(reg_wire)
    native_raw = [sub.recv_raw()]

    # TCP publisher
    tcp = socket.create_connection(("127.0.0.1", server.port))
    tcp.settimeout(5)
    parser = Parser()
    tcp.sendall(serialize(P.Connect(clientid="xp-tcp")))
    while not parser.feed(tcp.recv(4096)):
        pass
    tcp.sendall(serialize(P.Publish(topic="xp/t", payload=payloads[0])))
    native_raw.append(sub.recv_raw())

    # WS publisher (masked frames, the oracle codec)
    ws = socket.create_connection(("127.0.0.1", server.ws_port))
    ws.settimeout(5)
    key = b64.b64encode(_os.urandom(16)).decode()
    ws.sendall((f"GET /mqtt HTTP/1.1\r\nHost: x\r\n"
                "Upgrade: websocket\r\nConnection: Upgrade\r\n"
                f"Sec-WebSocket-Key: {key}\r\n"
                "Sec-WebSocket-Version: 13\r\n\r\n").encode())
    resp = b""
    while b"\r\n\r\n" not in resp:
        resp += ws.recv(4096)
    head, rest = resp.split(b"\r\n\r\n", 1)
    assert b"101" in head.split(b"\r\n")[0]
    dec = FrameDecoder(require_mask=False)
    wparser = Parser()
    ws.sendall(encode_frame(OP_BINARY,
                            serialize(P.Connect(clientid="xp-ws")),
                            mask=True))
    connacked = False
    if rest:
        for op, pl in dec.feed(rest):
            if op == OP_BINARY and wparser.feed(pl):
                connacked = True
    while not connacked:
        for op, pl in dec.feed(ws.recv(4096)):
            if op == OP_BINARY and wparser.feed(pl):
                connacked = True
    ws.sendall(encode_frame(
        OP_BINARY, serialize(P.Publish(topic="xp/t",
                                       payload=payloads[1])),
        mask=True))
    native_raw.append(sub.recv_raw())

    # SN publisher (the SN oracle codec)
    snf = SN.Frame()
    sn = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    sn.settimeout(5)
    sn.connect(("127.0.0.1", server.sn_port))
    sn.send(snf.serialize(SN.SnMessage(SN.CONNECT, flags=SN.F_CLEAN,
                                       duration=60, clientid="xp-sn")))
    ack = snf.parse(sn.recv(2048), None)[0][0]
    assert ack.type == SN.CONNACK and ack.rc == 0
    sn.send(snf.serialize(SN.SnMessage(SN.REGISTER, msg_id=1,
                                       topic_name="xp/t")))
    ra = snf.parse(sn.recv(2048), None)[0][0]
    assert ra.type == SN.REGACK and ra.rc == 0
    sn.send(snf.serialize(SN.SnMessage(
        SN.PUBLISH, flags=SN.qos_flags(0), topic_id=ra.topic_id,
        data=payloads[2])))
    native_raw.append(sub.recv_raw())

    # CoAP publisher
    cpub = CoapSock(server.coap_port)
    cpub.request(C.PUT, "ps/xp/t", payload=payloads[3],
                 queries=["clientid=xp-coap"])
    assert cpub.recv().code == C.CHANGED
    native_raw.append(sub.recv_raw())
    for s in (tcp, ws, sn):
        s.close()
    sub.close()
    cpub.close()

    # -- oracle arm: the asyncio gateway, same registration bytes,
    # same payload sequence (dispatched through the broker like any
    # cross-protocol publish reaching the gateway channel)
    from emqx_tpu.app import BrokerApp

    oracle_raw: list = []
    done = threading.Event()

    def oracle_main():
        async def run():
            oapp = BrokerApp()
            gw = oapp.gateway.load(C.CoapGateway(port=0))
            await gw.start_listeners()
            loop = asyncio.get_running_loop()
            q: asyncio.Queue = asyncio.Queue()

            class Proto(asyncio.DatagramProtocol):
                def datagram_received(self, data, addr):
                    q.put_nowait(data)

            tr, _ = await loop.create_datagram_endpoint(
                Proto, remote_addr=("127.0.0.1", gw.port))
            tr.sendto(reg_wire)
            oracle_raw.append(await asyncio.wait_for(q.get(), 5))
            for body in payloads:
                oapp.cm.dispatch(oapp.broker.publish(
                    Message(topic="xp/t", payload=body)))
                oracle_raw.append(await asyncio.wait_for(q.get(), 5))
            tr.close()
            await gw.stop_listeners()
        asyncio.run(run())
        done.set()

    th = threading.Thread(target=oracle_main)
    th.start()
    th.join(timeout=30)
    assert done.is_set(), "oracle arm did not complete"
    assert len(native_raw) == len(oracle_raw) == 5
    for i, (nb, ob) in enumerate(zip(native_raw, oracle_raw)):
        assert nb == ob, (
            f"datagram {i} drifted:\n  native: {nb!r}\n  oracle: {ob!r}")


# ---------------------------------------------------------------------------
# LwM2M over the native CoAP transport (the oracle-punt seam)
# ---------------------------------------------------------------------------

def test_lwm2m_register_observe_e2e_over_native_transport(app):
    """gateway/lwm2m.py stays asyncio-shaped, but its register/observe
    flows run end-to-end over the NATIVE CoAP transport: /rd exchanges
    punt whole to the LwM2M channel (coap_oracle=), downlink observe
    commands reach the device as CON POSTs through the native datagram
    socket, and device notifies publish uplink."""
    import json

    from emqx_tpu.broker.native_server import NativeBrokerServer
    from emqx_tpu.core.message import Message
    from emqx_tpu.gateway import lwm2m as L

    uplinks = []
    app.hooks.add("message.publish",
                  lambda m: uplinks.append(
                      (m.topic, bytes(m.payload))) or None,
                  priority=-500)
    srv = NativeBrokerServer(port=0, app=app, coap_port=0,
                             coap_oracle=lambda ctx: L.Channel(ctx))
    srv.start()
    try:
        dev = CoapSock(srv.coap_port)
        dev.request(C.POST, "rd", payload=b"</1/0>,</3/0>",
                    queries=["ep=dev-9", "lt=120", "lwm2m=1.0"])
        created = dev.recv()
        assert created.code == C.CREATED
        loc = [v.decode() for v in created.opts(C.OPT_LOCATION_PATH)]
        assert loc[0] == "rd" and len(loc) == 2
        deadline = time.time() + 3
        while time.time() < deadline and not any(
                t == "lwm2m/dev-9/up/register" for t, _ in uplinks):
            time.sleep(0.05)
        reg = json.loads([p for t, p in uplinks
                          if t == "lwm2m/dev-9/up/register"][0])
        assert {o["path"] for o in reg["objects"]} == {"/1/0", "/3/0"}
        assert srv.host.stats()["coap_punts"] >= 1

        # downlink observe command -> the device receives a CON POST
        # over the native transport; its ACK settles the command and
        # surfaces the response uplink
        app.cm.dispatch(app.broker.publish(Message(
            topic="lwm2m/dev-9/dn/observe",
            payload=json.dumps({"reqID": 7, "msgType": "observe",
                                "data": {"path": "/3/0/0"}}).encode())))
        cmd = dev.recv()
        assert cmd.type == C.CON and cmd.code == C.POST
        assert cmd.uri_path()[0] == "dn"
        dev.send(C.CoapMessage(C.ACK, C.CHANGED, cmd.mid, cmd.token,
                               [], b"ok"))
        deadline = time.time() + 3
        while time.time() < deadline and not any(
                t == "lwm2m/dev-9/up/response" for t, _ in uplinks):
            time.sleep(0.05)
        resp = json.loads([p for t, p in uplinks
                           if t == "lwm2m/dev-9/up/response"][-1])
        assert resp["reqID"] == 7 and resp["msgType"] == "observe"

        # device-originated notify publishes the uplink
        dev.request(C.POST, f"rd/{loc[1]}/notify", payload=b"23.5",
                    queries=["path=/3/0/0"])
        assert dev.recv().code == C.CHANGED
        deadline = time.time() + 3
        while time.time() < deadline and not any(
                t == "lwm2m/dev-9/up/notify" for t, _ in uplinks):
            time.sleep(0.05)
        note = json.loads([p for t, p in uplinks
                           if t == "lwm2m/dev-9/up/notify"][-1])
        assert note["payload"] == "23.5"
        dev.close()
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# faultline coverage: the CoAP datagram seams
# ---------------------------------------------------------------------------

def test_fault_conn_read_loses_datagrams(server, app):
    """conn_read errno armed against the CoAP ingress: exactly n
    datagrams are lost, every fire counted + ledger-visible."""
    c = CoapSock(server.coap_port)
    c.request(C.POST, "ps/fr/t", payload=b"ok",
              queries=["clientid=c-fr"])
    assert c.recv().code == C.CHANGED
    fired0 = server.host.fault_fired("conn_read")
    server.fault_arm("conn_read", "errno", n_or_prob=2)
    time.sleep(0.3)
    try:
        for i in range(2):
            c.request(C.POST, "ps/fr/t", payload=b"lost%d" % i,
                      queries=["clientid=c-fr"])
        with pytest.raises(socket.timeout):
            c.recv(timeout=0.8)        # both datagrams vanished
        assert server.host.fault_fired("conn_read") - fired0 == 2
        # the seam heals once the counted arm is spent
        c.request(C.POST, "ps/fr/t", payload=b"alive",
                  queries=["clientid=c-fr"])
        assert c.recv(timeout=5).code == C.CHANGED
        m = app.broker.metrics
        deadline = time.time() + 3
        while time.time() < deadline and m.val("messages.ledger.fault") < 2:
            time.sleep(0.05)
        assert m.val("messages.ledger.fault") >= 2
    finally:
        server.fault_disarm("conn_read")
    c.close()


def test_fault_conn_write_blackhole_forces_con_exhaustion(server, app):
    """conn_write blackhole scoped to the observer's conn: CON notifies
    vanish into the void (claimed sent, never delivered), retransmit
    to exhaustion, and the give-up lands in faults.* AND the ledger."""
    server.host.set_coap_ack_timeout(100)
    time.sleep(0.3)
    try:
        sub = CoapSock(server.coap_port)
        sub.observe("bh/t", token=b"bh", cid="c-bh", qos=1)
        # resolve the observer's conn id (the only coap:* conn w/ c-bh)
        deadline = time.time() + 3
        sub_conn = None
        while time.time() < deadline and sub_conn is None:
            for cid, conn in list(server.conns.items()):
                if conn.coap and conn.channel.clientid == "c-bh":
                    sub_conn = cid
            time.sleep(0.05)
        assert sub_conn is not None
        fired0 = server.host.fault_fired("conn_write")
        server.fault_arm("conn_write", "blackhole", key=sub_conn)
        try:
            pub = CoapSock(server.coap_port)
            pub.request(C.PUT, "ps/bh/t", payload=b"void",
                        queries=["clientid=c-bhp"])
            assert pub.recv().code == C.CHANGED
            with pytest.raises(socket.timeout):
                sub.recv(timeout=1.0)  # the notify went into the void
            deadline = time.time() + 8
            while (time.time() < deadline
                   and server.host.stats()["coap_giveups"] < 1):
                time.sleep(0.1)
            st = server.host.stats()
            assert st["coap_giveups"] >= 1
            assert server.host.fault_fired("conn_write") > fired0
            m = app.broker.metrics
            deadline = time.time() + 3
            while (time.time() < deadline
                   and m.val("messages.ledger.coap_giveup") < 1):
                time.sleep(0.05)
            assert m.val("messages.ledger.coap_giveup") >= 1
            assert m.val("messages.ledger.fault") >= 1
        finally:
            server.fault_disarm("conn_write")
        sub.close()
        pub.close()
    finally:
        server.host.set_coap_ack_timeout(0)


def test_fault_conn_write_short_sends_prefix_of_batch(server):
    """conn_write short against the datagram egress: only the first
    datagram of a batch goes out on the fired flush; the tail follows
    on the next (whole datagrams — never a torn CoAP message)."""
    sub = CoapSock(server.coap_port)
    sub.observe("sh/t", token=b"sh", cid="c-sh")
    pub = CoapSock(server.coap_port)
    fired0 = server.host.fault_fired("conn_write")
    server.fault_arm("conn_write", "short", n_or_prob=1)
    time.sleep(0.2)
    try:
        for i in range(3):
            pub.request(C.PUT, "ps/sh/t", payload=b"s%d" % i,
                        queries=["clientid=c-shp"])
            assert pub.recv(timeout=5).code == C.CHANGED
        got = sorted(sub.recv(timeout=5).payload for _ in range(3))
        assert got == [b"s0", b"s1", b"s2"]
        assert server.host.fault_fired("conn_write") >= fired0
    finally:
        server.fault_disarm("conn_write")
    sub.close()
    pub.close()


def test_oracle_channel_teardown_spares_live_native_session(server, app):
    """Review regression: a punted-exchange oracle channel that never
    owned the CM slot (a native conn holds the clientid) must not
    strip the LIVE session's subscriptions when its conn dies — its
    close_session is guarded by CM ownership."""
    sub = CoapSock(server.coap_port)
    sub.observe("guard/t", token=b"gd", cid="c-guard")
    # a SECOND endpoint claims the same clientid through the punt seam
    # (a Block1 upload is oracle-served; _ensure_client registers there)
    other = CoapSock(server.coap_port)
    other.request(C.POST, "ps/guard/up", payload=b"A" * 16,
                  options=[(C.OPT_BLOCK1, C.encode_block(0, 1, 16))],
                  queries=["clientid=c-guard"])
    assert other.recv().code == C.CONTINUE_231
    # find + kill the punting endpoint's conn (the one holding an
    # oracle channel): its terminate runs, and the guard must leave
    # c-guard's broker state alone
    victim = None
    deadline = time.time() + 3
    while time.time() < deadline and victim is None:
        with server._coap_lock:
            ids = list(server._coap_oracle)
        victim = ids[0] if ids else None
        time.sleep(0.05)
    assert victim is not None
    server.host.close_conn(victim)
    time.sleep(0.4)
    pub = CoapSock(server.coap_port)
    pub.request(C.PUT, "ps/guard/t", payload=b"still-here",
                queries=["clientid=c-gpub"])
    assert pub.recv().code == C.CHANGED
    assert sub.recv().payload == b"still-here"
    sub.close()
    other.close()
    pub.close()


# ---------------------------------------------------------------------------
# degradation ladder: the asyncio gateway still serves when coap_port off
# ---------------------------------------------------------------------------

def test_asyncio_gateway_fallback(app):
    """NativeBrokerServer without coap_port + the asyncio CoapGateway
    side-by-side: the deployment fallback stays fully functional."""
    import asyncio
    import threading

    from emqx_tpu.broker.native_server import NativeBrokerServer

    srv = NativeBrokerServer(port=0, app=app)
    srv.start()
    state: dict = {}
    stop = threading.Event()
    ready = threading.Event()

    def gw_main():
        async def run_gw():
            gw = app.gateway.load(C.CoapGateway(port=0))
            await gw.start_listeners()
            state["port"] = gw.port
            ready.set()
            while not stop.is_set():
                await asyncio.sleep(0.05)
            await gw.stop_listeners()
        asyncio.run(run_gw())

    th = threading.Thread(target=gw_main)
    th.start()
    try:
        assert srv.coap_port is None
        assert ready.wait(10)
        c = CoapSock(state["port"])
        c.request(C.POST, "ps/fb/t", payload=b"v",
                  queries=["clientid=c-fb"])
        assert c.recv().code == C.CHANGED
        c.close()
    finally:
        stop.set()
        th.join()
        srv.stop()
