"""Native ack plane (round 6): batched QoS1 PUBACK bookkeeping, the
below-the-GIL QoS2 exchange's window accounting, and the ordering
seams around it.

The C++ host (native/src/host.cc) owns pid allocation, the inflight
bitmaps and the window-full pending queue for every elevated-qos
delivery; Python sees ONE kind-7 ack record per poll cycle
(broker/native_server.py _on_ack_batch) instead of per-message
bookkeeping. Reference anchors: emqx_session.erl:432-530 (ack
lifecycle), emqx_inflight.erl (window), emqx_mqueue.erl (overflow).
"""

import asyncio
import socket
import struct
import time

import pytest

from emqx_tpu import native

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native lib unavailable")

from emqx_tpu.app import BrokerApp            # noqa: E402
from emqx_tpu.broker.native_server import NativeBrokerServer  # noqa: E402
from emqx_tpu.mqtt import packet as P         # noqa: E402
from emqx_tpu.mqtt.client import MqttClient   # noqa: E402


def run(coro):
    asyncio.run(coro)


async def _settle(seconds=0.4):
    await asyncio.sleep(seconds)


# -- windowed QoS1 smoke (ISSUE 1 satellite: counters move, window holds) ----

def test_qos1_windowed_smoke_counters_and_window():
    """A windowed QoS1 load run on the native plane: the qos1/puback
    counters advance, batched ack records flow, and the native inflight
    occupancy never exceeds the receive-maximum budget (the dynamic
    split leaves the Python session at least one slot, so the native
    cap is always < budget)."""
    budget = 64
    server = NativeBrokerServer(port=0, app=BrokerApp(),
                                session_opts={"max_inflight": budget})
    server.start()
    try:
        res = native.loadgen_run(
            "127.0.0.1", server.port, n_subs=2, n_pubs=2,
            msgs_per_pub=2000, qos=1, payload_len=16, window=64)
        assert res["received"] == res["sent"] == 4000, res
        assert res["acks"] == 4000, res          # publisher PUBACKs
        st = server.fast_stats()
        assert st["qos1_in"] > 0, st             # native qos1 publishes
        assert st["native_acks"] > 0, st         # subscriber PUBACKs eaten
        assert st["ack_batches"] > 0, st         # batched records emitted
        # drain the last cycle's record, then check the plane's view
        time.sleep(0.3)
        ap = server.ack_plane
        assert ap["batches"] > 0 and ap["acked"] > 0, ap
        # receive-maximum held: the native cap can grow past the half
        # split but never to the full budget (Python keeps >= 1 slot)
        assert ap["max_inflight_seen"] < budget, ap
        assert st["drops_inflight"] == 0, st
    finally:
        server.stop()


# -- batched ack records reconcile the Python session ------------------------

def test_ack_records_reconcile_session_gauges():
    """kind-7 records land in session.native_ack_sync: the session's
    native gauges (occupancy, cumulative acked) reflect the C++ window
    without any per-message Python work, and session.info() surfaces
    them."""
    server = NativeBrokerServer(port=0, app=BrokerApp())
    server.start()

    async def main():
        sub = MqttClient(port=server.port, clientid="ars")
        await sub.connect()
        await sub.subscribe("ar/t", qos=1)
        pub = MqttClient(port=server.port, clientid="arp")
        await pub.connect()
        await pub.publish("ar/t", b"warm", qos=1)   # slow path, earns permit
        await sub.recv(timeout=10)
        await _settle(0.5)
        for i in range(5):
            await pub.publish("ar/t", f"m{i}".encode(), qos=1)
            m = await sub.recv(timeout=10)
            assert m.packet_id is None or m.packet_id >= 32768
        await _settle(0.5)
        sess = next(c.channel.session for c in server.conns.values()
                    if c.channel.clientid == "ars")
        assert sess.native_acked >= 1, sess.info()
        assert sess.native_inflight == 0, sess.info()  # all acked
        info = sess.info()
        assert "native_inflight_cnt" in info and "native_acked_cnt" in info
        # the node metrics got the batched folds too
        m = server.broker.metrics
        assert m.val("messages.native.acked") >= 1
        assert m.val("messages.acked") >= 1
        await sub.close(); await pub.close()

    run(main())
    server.stop()


def test_qos2_native_counters_move():
    """The native QoS2 exchange advances its dedicated stats: qos2_in
    (publishes owned natively) and qos2_rel (PUBREL→PUBCOMP exchanges
    completed), merged into messages.qos2.received per housekeep."""
    server = NativeBrokerServer(port=0, app=BrokerApp())
    server.start()

    async def main():
        sub = MqttClient(port=server.port, clientid="qcs")
        await sub.connect()
        await sub.subscribe("qc/t", qos=2)
        pub = MqttClient(port=server.port, clientid="qcp")
        await pub.connect()
        await pub.publish("qc/t", b"warm", qos=2)
        await sub.recv(timeout=10)
        await _settle(0.5)
        for i in range(3):
            await pub.publish("qc/t", f"m{i}".encode(), qos=2)
            await sub.recv(timeout=10)
            await _settle(0.15)
        st = server.fast_stats()
        assert st["qos2_in"] >= 1, st
        assert st["qos2_rel"] >= 1, st
        await sub.close(); await pub.close()

    run(main())
    server.stop()


# -- LaneDeliver ordering regression (ISSUE 1 satellite #1) ------------------

def _mqtt_connect(cid: bytes) -> bytes:
    vh = b"\x00\x04MQTT\x04\x02\x00\x3c" + struct.pack(">H", len(cid)) + cid
    return bytes([0x10, len(vh)]) + vh


def _mqtt_publish(topic: bytes, payload: bytes, qos=0, pid=0) -> bytes:
    body = struct.pack(">H", len(topic)) + topic
    if qos:
        body += struct.pack(">H", pid)
    body += payload
    return bytes([0x30 | (qos << 1), len(body)]) + body


def test_lane_poison_ordering_last_parked_frame_must_punt():
    """Regression for the LaneDeliver ordering race: punting frame A of
    a topic poisons it while frame B is still parked; resolving B used
    to erase the poison (LaneForget) BEFORE checking it, letting B
    deliver natively and overtake A in Python's FIFO. Both frames must
    come up as punts, in arrival order, with zero native deliveries."""
    host = native.NativeHost(port=0, max_size=1 << 16)
    try:
        ids = []

        def pump(deadline_s=5.0, want_opens=0, want_frames=0):
            frames = []
            t0 = time.time()
            while time.time() - t0 < deadline_s:
                for kind, conn, payload in host.poll(50):
                    if kind == native.EV_OPEN:
                        ids.append(conn)
                    elif kind == native.EV_FRAME:
                        frames.append((conn, payload))
                if len(ids) >= want_opens and len(frames) >= want_frames:
                    break
            return frames

        pub = socket.create_connection(("127.0.0.1", host.port))
        pump(want_opens=1)
        sub = socket.create_connection(("127.0.0.1", host.port))
        pump(want_opens=2)
        pub_id, sub_id = ids
        pub.sendall(_mqtt_connect(b"lpp"))
        sub.sendall(_mqtt_connect(b"lps"))
        pump(want_opens=2, want_frames=2)      # drain the CONNECT frames

        host.enable_fast(pub_id, 4, 64)
        host.enable_fast(sub_id, 4, 64)
        host.sub_add(sub_id, "lp/t", 0, 0)
        host.permit(pub_id, "lp/t")
        host.set_lane(True)
        list(host.poll(50))                    # apply the control ops

        pub.sendall(_mqtt_publish(b"lp/t", b"m1")
                    + _mqtt_publish(b"lp/t", b"m2"))
        lane = []
        t0 = time.time()
        while len(lane) < 2 and time.time() - t0 < 5:
            for kind, conn, payload in host.poll(50):
                if kind == native.EV_LANE:
                    lane.append(conn)          # conn field = lane seq
        assert len(lane) == 2, lane
        seq1, seq2 = lane

        # frame 1: nondeterministic punt (pump-failure flag) → poison
        host.lane_deliver(struct.pack("<IQBH", 1, seq1, 1, 0))
        # frame 2: CLEAN verdict naming the subscribed filter — the
        # pre-fix code would deliver this natively, overtaking frame 1
        filt = b"lp/t"
        host.lane_deliver(struct.pack("<IQBH", 1, seq2, 0, 1)
                          + struct.pack("<H", len(filt)) + filt)

        punts = pump(want_frames=2)
        assert len(punts) == 2, punts
        assert [c for c, _ in punts] == [pub_id, pub_id]
        assert punts[0][1].endswith(b"m1") and punts[1][1].endswith(b"m2"), \
            punts                              # arrival order preserved
        st = host.stats()
        assert st["lane_punts"] >= 2, st
        assert st["fast_out"] == 0, st         # nothing delivered natively
        sub.settimeout(0.3)
        try:
            data = sub.recv(4096)
            assert not data, data              # no overtaking delivery
        except socket.timeout:
            pass
        pub.close()
        sub.close()
        for _ in range(5):
            list(host.poll(10))
    finally:
        host.destroy()


# -- shutdown discipline (ISSUE 1 satellite #2) ------------------------------

def test_stop_produces_no_poll_step_noise(caplog):
    """server.stop() must signal the poll thread BEFORE tearing down
    the tick executor/host: the old order could log 'native poll step
    failed' with 'cannot schedule new futures after shutdown' when a
    step outlived the joins. A stop under live traffic must be silent."""
    import logging

    server = NativeBrokerServer(port=0, app=BrokerApp())
    server.start()

    async def main():
        sub = MqttClient(port=server.port, clientid="sps")
        await sub.connect()
        await sub.subscribe("sp/t", qos=1)
        pub = MqttClient(port=server.port, clientid="spp")
        await pub.connect()
        for i in range(20):
            await pub.publish("sp/t", b"x", qos=1)
        await sub.recv(timeout=10)
        await sub.close(); await pub.close()

    run(main())
    with caplog.at_level(logging.ERROR, logger="emqx_tpu.native_server"):
        server.stop()
    assert not [r for r in caplog.records
                if "poll step failed" in r.getMessage()], caplog.records
    # idempotent: a second stop must not blow up on the dead handles
    server.stop()


def test_qos2_dup_across_permit_promotion_does_not_double_deliver():
    """Regression: the FIRST QoS2 publish on a topic runs the Python
    exchange AND earns the permit. If the client never sees our PUBREC
    and retransmits with DUP after the permit landed, the native plane
    must NOT treat it as a fresh publish (its awaiting-rel bitmap is
    empty — the PYTHON session owns pid's exactly-once state): the dup
    forwards to Python, which re-answers PUBREC, and the subscriber
    receives exactly once."""
    server = NativeBrokerServer(port=0, app=BrokerApp())
    server.start()

    async def main():
        sub = MqttClient(port=server.port, clientid="pps")
        await sub.connect()
        await sub.subscribe("pp/t", qos=2)
        pub = MqttClient(port=server.port, clientid="ppp")
        await pub.connect()
        pid = 77
        # first-ever publish on pp/t: Python exchange + permit earn;
        # PUBREC is "lost" (we just don't complete with PUBREL yet)
        await pub._send(P.Publish(topic="pp/t", payload=b"once", qos=2,
                                  packet_id=pid, properties={}))
        await pub._expect(P.PUBREC, 10)
        assert (await sub.recv(timeout=10)).payload == b"once"
        await _settle(0.6)                    # permit grant window
        fast0 = server.fast_stats()["fast_in"]
        await pub._send(P.Publish(topic="pp/t", payload=b"once", qos=2,
                                  packet_id=pid, dup=True, properties={}))
        rec = await pub._expect(P.PUBREC, 10)
        assert rec.packet_id == pid
        with pytest.raises(asyncio.TimeoutError):
            await sub.recv(timeout=0.8)       # exactly once
        assert server.fast_stats()["fast_in"] == fast0  # dup stayed slow
        await pub._send(P.PubRel(packet_id=pid))
        await pub._expect(P.PUBCOMP, 10)      # Python completes its state
        # the permit still serves FRESH publishes natively
        await pub.publish("pp/t", b"fresh", qos=2)
        m = await sub.recv(timeout=10)
        assert m.payload == b"fresh" and m.packet_id >= 32768
        await sub.close(); await pub.close()

    run(main())
    server.stop()


# -- live plane handoff (round 10: the old strict-xfail, now green) ----------

def test_qos2_exactly_once_across_live_plane_demotion():
    """kDisableFast no longer resets the AckState into the void: the
    kind-11 handoff hands the publisher's awaiting-rel ids to the
    Python session (session.adopt_native_window), so a QoS2 retransmit
    straddling the demotion dedups there — PUBREC, no second delivery —
    and the client's PUBREL completes through the Python exchange."""
    server = NativeBrokerServer(port=0, app=BrokerApp())
    server.start()

    async def main():
        sub = MqttClient(port=server.port, clientid="dms")
        await sub.connect()
        await sub.subscribe("dm/t", qos=2)
        pub = MqttClient(port=server.port, clientid="dmp")
        await pub.connect()
        await pub.publish("dm/t", b"warm", qos=2)    # earn the permit
        await sub.recv(timeout=10)
        await _settle(0.5)
        pid = 55
        await pub._send(P.Publish(topic="dm/t", payload=b"once", qos=2,
                                  packet_id=pid, properties={}))
        rec = await pub._expect(P.PUBREC, 10)
        assert rec.packet_id == pid
        await sub.recv(timeout=10)                   # first delivery
        # demote the publisher's native plane mid-exchange
        conn_id = server._fast_conn_of["dmp"]
        server.host.disable_fast(conn_id)
        await _settle(0.4)
        assert server.fast_stats()["handoffs"] >= 1
        sess = next(c.channel.session for c in server.conns.values()
                    if c.channel.clientid == "dmp")
        assert pid in sess.awaiting_rel, sess.awaiting_rel
        # DUP retransmit across the demotion: the adopted awaiting-rel
        # id dedups it — PUBREC answered, nothing re-delivered
        await pub._send(P.Publish(topic="dm/t", payload=b"once", qos=2,
                                  packet_id=pid, dup=True, properties={}))
        await pub._expect(P.PUBREC, 10)
        with pytest.raises(asyncio.TimeoutError):
            await sub.recv(timeout=0.8)
        # the exchange completes on the Python plane
        await pub._send(P.PubRel(packet_id=pid))
        comp = await pub._expect(P.PUBCOMP, 10)
        assert comp.packet_id == pid
        assert pid not in sess.awaiting_rel
        await sub.close(); await pub.close()

    run(main())
    server.stop()


def test_qos2_exactly_once_across_promotion_handoff():
    """The symmetric case: an exchange the PYTHON session owns stays
    Python-owned across a re-promotion (server.promote) — its DUP
    retransmit and PUBREL forward to the session (the native
    awaiting-rel set doesn't own the id), so nothing double-delivers —
    while fresh publishes return to the fast path."""
    server = NativeBrokerServer(port=0, app=BrokerApp())
    server.start()

    async def main():
        sub = MqttClient(port=server.port, clientid="pms")
        await sub.connect()
        await sub.subscribe("pm/t", qos=2)
        pub = MqttClient(port=server.port, clientid="pmp")
        await pub.connect()
        await pub.publish("pm/t", b"warm", qos=2)    # earn the permit
        await sub.recv(timeout=10)
        await _settle(0.5)
        # demote, then open a Python-owned exchange while slow
        conn_id = server._fast_conn_of["pmp"]
        server.host.disable_fast(conn_id)
        await _settle(0.4)
        pid = 66
        await pub._send(P.Publish(topic="pm/t", payload=b"slowq2", qos=2,
                                  packet_id=pid, properties={}))
        await pub._expect(P.PUBREC, 10)
        assert (await sub.recv(timeout=10)).payload == b"slowq2"
        # promote with the exchange still open
        assert server.promote("pmp")
        await _settle(0.4)
        # DUP retransmit post-promotion: the native plane must forward
        # it (it does not own pid 66) and the session dedups
        await pub._send(P.Publish(topic="pm/t", payload=b"slowq2", qos=2,
                                  packet_id=pid, dup=True, properties={}))
        await pub._expect(P.PUBREC, 10)
        with pytest.raises(asyncio.TimeoutError):
            await sub.recv(timeout=0.8)
        await pub._send(P.PubRel(packet_id=pid))
        await pub._expect(P.PUBCOMP, 10)
        # the fast plane is back: re-earn the permit once, then the
        # next publish runs natively (native pid space >= 32768)
        await pub.publish("pm/t", b"re-earn", qos=2)
        await sub.recv(timeout=10)
        await _settle(0.5)
        await pub.publish("pm/t", b"fresh", qos=2)
        m = await sub.recv(timeout=10)
        assert m.payload == b"fresh" and m.packet_id >= 32768, m
        await sub.close(); await pub.close()

    run(main())
    server.stop()


def test_demotion_hands_pending_frames_to_the_session_mqueue():
    """A demotion with window-full pending deliveries must not lose
    them: the kind-11 sub-2 records re-enqueue the parked frames into
    the Python session's mqueue, and the client's acks drain them out
    through the Python window (the retransmit-on-reconnect seam)."""
    import socket
    import struct

    host = native.NativeHost(port=0, max_size=1 << 16)
    try:
        ids = []

        def pump(deadline_s=5.0, want_opens=0, want_frames=0):
            frames = []
            t0 = time.time()
            while time.time() - t0 < deadline_s:
                for kind, conn, payload in host.poll(50):
                    if kind == native.EV_OPEN:
                        ids.append(conn)
                    elif kind == native.EV_FRAME:
                        frames.append((conn, payload))
                if len(ids) >= want_opens and len(frames) >= want_frames:
                    break
            return frames

        pub = socket.create_connection(("127.0.0.1", host.port))
        pump(want_opens=1)
        sub = socket.create_connection(("127.0.0.1", host.port))
        pump(want_opens=2)
        pub_id, sub_id = ids
        pub.sendall(_mqtt_connect(b"hop"))
        sub.sendall(_mqtt_connect(b"hos"))
        pump(want_opens=2, want_frames=2)

        host.enable_fast(pub_id, 4, 0)
        host.enable_fast(sub_id, 4, 2)     # native window of TWO
        host.sub_add(sub_id, "ho/t", 1, 0)
        host.permit(pub_id, "ho/t")
        list(host.poll(50))

        # 5 qos1 publishes: 2 fill the window, 3 park on pending
        frames = b"".join(
            _mqtt_publish(b"ho/t", b"m%d" % i, qos=1, pid=10 + i)
            for i in range(5))
        pub.sendall(frames)
        t0 = time.time()
        while time.time() - t0 < 5:
            list(host.poll(20))
            st = host.stats()
            if st["fast_out"] >= 2:
                break
        host.disable_fast(sub_id)
        handoff = {"awaiting": [], "inflight": [], "pending": []}
        t0 = time.time()
        while time.time() - t0 < 5 and len(handoff["pending"]) < 3:
            for kind, conn, payload in host.poll(50):
                if kind == native.EV_HANDOFF:
                    assert conn == sub_id
                    part = native.parse_handoff(payload)
                    for k in handoff:
                        handoff[k] += part[k]
        assert len(handoff["inflight"]) == 2, handoff
        assert all(pid >= 32768 for pid, _q, _p in handoff["inflight"])
        assert all(q == 1 and ph == "publish"
                   for _pid, q, ph in handoff["inflight"])
        assert len(handoff["pending"]) == 3, handoff
        for frame in handoff["pending"]:
            assert frame[0] >> 4 == 3           # serialized PUBLISH
            tlen = (frame[2] << 8) | frame[3]
            assert frame[4:4 + tlen] == b"ho/t"
        pub.close()
        sub.close()
        for _ in range(5):
            list(host.poll(10))
    finally:
        host.destroy()
