"""Real-gRPC exhook: the hand-written proto codec differentially
checked against the official google.protobuf runtime, and the full
broker hook chain driven through a grpcio HookProvider — the
emqx_exhook_demo_svr / emqx_exhook_SUITE analogue over the actual wire
(apps/emqx_exhook/priv/protos/exhook.proto)."""

import asyncio

import pytest

grpc = pytest.importorskip("grpc")

from emqx_tpu.app import BrokerApp
from emqx_tpu.broker.server import BrokerServer
from emqx_tpu.config.config import Config
from emqx_tpu.exhook import pbwire
from emqx_tpu.exhook.grpc_transport import GrpcConn, GrpcHookProvider
from emqx_tpu.exhook.server import ExhookMgr, ExhookServer
from emqx_tpu.mqtt.client import MqttClient


# -- codec vs official protobuf runtime ----------------------------------------

def _dyn_message(name: str, schema: dict, pool, factory):
    """Build a google.protobuf message class from one of our schema
    tables (the independent oracle for field numbers/wire types)."""
    from google.protobuf import descriptor_pb2

    fd = descriptor_pb2.FileDescriptorProto()
    fd.name = f"dyn_{name.lower()}.proto"
    fd.package = "dyn"
    fd.syntax = "proto3"
    msg = fd.message_type.add()
    msg.name = name
    T = descriptor_pb2.FieldDescriptorProto
    kinds = {"str": T.TYPE_STRING, "bytes": T.TYPE_BYTES,
             "u32": T.TYPE_UINT32, "u64": T.TYPE_UINT64,
             "i64": T.TYPE_INT64, "bool": T.TYPE_BOOL,
             "enum": T.TYPE_INT32}
    for num, spec in sorted(schema.items()):
        fname, kind = spec[0], spec[1]
        f = msg.field.add()
        f.name = fname
        f.number = num
        if isinstance(kind, tuple):        # repeated str only, here
            f.label = T.LABEL_REPEATED
            f.type = kinds[kind[1]]
        elif kind == "map_ss":
            # maps are repeated entry messages; model as such
            entry = msg.nested_type.add()
            entry.name = f"{fname.capitalize()}Entry"
            entry.options.map_entry = True
            for i, n in ((1, "key"), (2, "value")):
                ef = entry.field.add()
                ef.name, ef.number, ef.type = n, i, T.TYPE_STRING
                ef.label = T.LABEL_OPTIONAL
            f.label = T.LABEL_REPEATED
            f.type = T.TYPE_MESSAGE
            f.type_name = f".dyn.{name}.{entry.name}"
        else:
            f.label = T.LABEL_OPTIONAL
            f.type = kinds[kind]
    file_desc = pool.Add(fd)
    return factory.GetMessageClass(file_desc.message_types_by_name[name])


def test_codec_differential_vs_protobuf_runtime():
    from google.protobuf import descriptor_pool, message_factory

    pool = descriptor_pool.DescriptorPool()
    factory = message_factory

    cases = [
        ("ClientInfo", pbwire.CLIENT_INFO,
         {"clientid": "c-1", "username": "u", "sockport": 1883,
          "is_superuser": True, "peerhost": "10.0.0.9"}),
        ("Message", pbwire.MESSAGE,
         {"id": "m1", "qos": 2, "from": "dev", "topic": "t/1",
          "payload": b"\x00\x01bin", "timestamp": 1700000000000,
          "headers": {"username": "u", "allow_publish": "true"}}),
        ("ConnInfo", pbwire.CONN_INFO,
         {"clientid": "c", "proto_name": "MQTT", "proto_ver": "5",
          "keepalive": 60}),
        ("RequestMeta", pbwire.REQUEST_META,
         {"node": "emqx@1.2.3.4", "version": "5.0.14",
          "cluster_name": "emqxcl"}),
        ("SubOpts", pbwire.SUB_OPTS,
         {"qos": 1, "share": "g1", "rh": 2, "rap": 1, "nl": 1}),
        ("HookSpec", pbwire.HOOK_SPEC,
         {"name": "message.publish", "topics": ["a/#", "b/+"]}),
    ]
    for name, schema, values in cases:
        cls = _dyn_message(name, schema, pool, factory)
        # our encoder → their decoder
        official = cls()
        official.ParseFromString(pbwire.encode(schema, values))
        for k, v in values.items():
            got = getattr(official, k)
            if isinstance(v, dict):
                assert dict(got) == v, (name, k)
            elif isinstance(v, list):
                assert list(got) == v, (name, k)
            else:
                assert got == v, (name, k)
        # their encoder → our decoder
        ours = pbwire.decode(schema, official.SerializeToString())
        for k, v in values.items():
            assert ours[k] == v, (name, k)


def test_valued_response_oneof_and_unknown_fields():
    # bool_result branch
    data = pbwire.encode(pbwire.VALUED_RESPONSE,
                         {"type": 2, "bool_result": True})
    out = pbwire.decode(pbwire.VALUED_RESPONSE, data)
    assert out["type"] == 2 and out["bool_result"] is True
    # a FALSE verdict must still appear on the wire (oneof presence):
    # a conformant peer distinguishes STOP+deny from no-answer
    deny = pbwire.encode(pbwire.VALUED_RESPONSE,
                         {"type": 2, "bool_result": False})
    assert bytes([3 << 3 | 0, 0]) in deny          # field 3, varint 0
    assert pbwire.decode(pbwire.VALUED_RESPONSE, deny)["bool_result"] \
        is False
    # ...and absence stays absent (no default fill for oneof members)
    assert "bool_result" not in pbwire.decode(
        pbwire.VALUED_RESPONSE,
        pbwire.encode(pbwire.VALUED_RESPONSE, {"type": 0}))
    # message branch
    data = pbwire.encode(pbwire.VALUED_RESPONSE, {
        "type": 2, "message": {"topic": "t", "payload": b"p"}})
    out = pbwire.decode(pbwire.VALUED_RESPONSE, data)
    assert out["message"]["topic"] == "t"
    # unknown fields (forward compat) are skipped, not fatal
    extra = data + bytes([15 << 3 | 0]) + b"\x07"     # field 15 varint
    assert pbwire.decode(pbwire.VALUED_RESPONSE, extra)["type"] == 2


# -- transport + provider end-to-end -------------------------------------------

class _Recorder:
    hooks = ["client.authenticate", "client.authorize", "message.publish",
             "client.connected", "session.subscribed",
             "client.disconnected"]

    def __init__(self):
        self.notified = []
        self.denied_user = "mallory"

    def on_client_authenticate(self, ci):
        if ci.get("username") == self.denied_user:
            return False
        return True if ci.get("username") == "trusted" else None

    def on_client_authorize(self, ci, action, topic):
        if topic.startswith("secret/"):
            return False
        return None

    def on_message_publish(self, msg):
        if msg["topic"] == "drop/me":
            return False
        if msg["topic"] == "rewrite/me":
            return {**msg, "topic": "rewritten/to",
                    "payload": b"new-" + msg["payload"]}
        return None

    def on_notify(self, rpc, request):
        self.notified.append((rpc, request))


def test_grpc_hook_provider_end_to_end():
    """CONNECT/auth/publish through a live broker with a gRPC provider:
    deny, allow-through, authz deny, drop, rewrite, notify RPCs."""
    handler = _Recorder()
    provider = GrpcHookProvider(handler).start()

    async def main():
        conf = Config()
        conf.init_load(
            'exhook { servers = [ { name = "p1", '
            f'url = "grpc://127.0.0.1:{provider.port}" }}, ] }}')
        app = BrokerApp.from_config(conf)
        assert app.exhook is not None
        assert app.exhook.servers["p1"].loaded
        server = BrokerServer(port=0, app=app)
        await server.start()
        try:
            bad = MqttClient(port=server.port, clientid="m1",
                             username="mallory", password=b"x")
            with pytest.raises(ConnectionRefusedError):
                await bad.connect()

            sub = MqttClient(port=server.port, clientid="s1",
                             username="trusted", password=b"x")
            await sub.connect()
            await sub.subscribe("#", qos=0)

            pub = MqttClient(port=server.port, clientid="p1",
                             username="trusted", password=b"x")
            await pub.connect()
            await pub.publish("rewrite/me", b"data")
            got = await sub.recv()
            assert got.topic == "rewritten/to"
            assert got.payload == b"new-data"

            await pub.publish("drop/me", b"x")
            await pub.publish("after/drop", b"ok")
            got = await sub.recv()
            assert got.topic == "after/drop"      # dropped one never came

            # authz deny via provider
            deny = await sub.subscribe("secret/x", qos=0)
            assert deny.reason_codes[0] >= 0x80

            await pub.disconnect()
            await sub.disconnect()
            await asyncio.sleep(0.2)
        finally:
            await server.stop()

    try:
        asyncio.run(main())
        rpcs = [r for r, _ in handler.notified]
        assert "OnClientConnected" in rpcs
        assert "OnSessionSubscribed" in rpcs
        assert "OnClientDisconnected" in rpcs
        # request contents decoded provider-side
        ci = next(req for r, req in handler.notified
                  if r == "OnClientConnected")["clientinfo"]
        assert ci["clientid"] in ("s1", "p1")
        assert provider.calls.count("OnProviderLoaded") == 1
    finally:
        provider.stop()


def test_grpc_failed_action_semantics():
    """Dead gRPC endpoint: failed_action=deny blocks the publish,
    ignore passes it through (emqx_exhook_server.erl:95-96,433)."""
    # occupy then free a port so nothing listens on it
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    dead_port = s.getsockname()[1]
    s.close()

    from emqx_tpu.core.message import Message

    for action, expect_delivery in (("ignore", True), ("deny", False)):
        app = BrokerApp()
        mgr = ExhookMgr()
        mgr.attach(app.hooks)
        server = ExhookServer("dead", "127.0.0.1", dead_port,
                              transport="grpc", timeout_s=0.5,
                              failed_action=action)
        server.loaded = True                       # simulate loaded-then-died
        server.hooks_wanted = ["message.publish"]
        mgr.servers["dead"] = server
        app.broker.subscribe("sess1", "t/#")
        deliveries = app.broker.publish(Message(topic="t/1", payload=b"x"))
        assert bool(deliveries) is expect_delivery, action


def test_bad_scheme_is_a_config_error():
    conf = Config()
    conf.init_load('exhook { servers = [ { name = "x", '
                   'url = "ftp://127.0.0.1:1" } ] }')
    with pytest.raises(ValueError, match="scheme"):
        BrokerApp.from_config(conf)
    with pytest.raises(ValueError, match="transport"):
        ExhookServer("x", "127.0.0.1", 1, transport="carrier-pigeon")


def test_provider_down_at_boot_reconnects_via_tick():
    """enable_async keeps an unreachable provider registered; tick()
    heals it once the provider comes up (reference auto_reconnect)."""
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()

    conf = Config()
    conf.init_load('exhook { servers = [ { name = "late", '
                   f'url = "grpc://127.0.0.1:{port}", '
                   'request_timeout = 0.5, auto_reconnect = 0.05 } ] }')
    app = BrokerApp.from_config(conf)           # boots despite dead provider
    server = app.exhook.servers["late"]
    assert not server.loaded

    handler = _Recorder()
    provider = GrpcHookProvider(handler, port=port).start()
    try:
        import time
        deadline = time.monotonic() + 5
        while not server.loaded and time.monotonic() < deadline:
            time.sleep(0.06)
            app.exhook.tick()
        assert server.loaded
        assert "message.publish" in server.hooks_wanted
    finally:
        provider.stop()


def test_batch_publish_lane_falls_back_to_per_message():
    """OnMessagePublishBatch over gRPC decomposes into per-message
    OnMessagePublish calls against a stock provider."""
    handler = _Recorder()
    provider = GrpcHookProvider(handler).start()
    try:
        conn = GrpcConn(("127.0.0.1", provider.port), 5.0)
        resp = conn.call("OnMessagePublishBatch", {"messages": [
            {"topic": "drop/me", "payload": b"a", "qos": 0},
            {"topic": "keep/me", "payload": b"b", "qos": 0},
            {"topic": "rewrite/me", "payload": b"c", "qos": 0}]})
        results = resp["results"]
        assert results[0].get("drop") is True
        assert "drop" not in results[1] and "message" not in results[1]
        assert results[2]["message"]["topic"] == "rewritten/to"
        conn.close()
    finally:
        provider.stop()
