"""Device trie matcher ↔ host oracle equivalence (the round-1 "aha" slice:
same results as the emqx_trie-semantics oracle on randomized filter sets)."""

import random

import numpy as np
import pytest

from emqx_tpu.core import topic as T
from emqx_tpu.router.index import TrieIndex
from emqx_tpu.router.trie import Trie
from emqx_tpu.ops import trie_match as tm


def build(filters, max_levels=10):
    idx = TrieIndex(max_levels=max_levels)
    idx.load(filters)
    arrays = idx.ensure()
    return idx, tm.device_trie(arrays)


def run_match(idx, trie_dev, topics, K=32):
    tokens, lengths, sys_flags, too_long = idx.tokenize(topics)
    assert not too_long
    cand, overflow, _ = tm.match_batch(
        trie_dev, np.asarray(tokens), np.asarray(lengths), np.asarray(sys_flags), K=K
    )
    cand = np.asarray(cand)
    out = []
    for b in range(len(topics)):
        fids = cand[b][cand[b] >= 0]
        assert len(set(fids.tolist())) == len(fids), "duplicate emission"
        out.append(sorted(idx.filters[f] for f in fids))
    return out, np.asarray(overflow)


def test_basic_match():
    filters = ["a/+/c", "a/#", "+/b/c", "#", "a/b/+", "a/b/c", "x"]
    idx, dev = build(filters)
    got, overflow = run_match(idx, dev, ["a/b/c", "a", "x", "q/r", "$SYS/x"])
    assert not overflow.any()
    assert got[0] == sorted(["a/+/c", "a/#", "+/b/c", "#", "a/b/+", "a/b/c"])
    assert got[1] == sorted(["a/#", "#"])
    assert got[2] == sorted(["#", "x"])
    assert got[3] == sorted(["#"])
    assert got[4] == []


def test_hash_matches_parent_and_empty_levels():
    filters = ["sport/#", "sport/+", "+/+", "a//c", "a/+/c"]
    idx, dev = build(filters)
    got, _ = run_match(idx, dev, ["sport", "sport/", "a//c", "sport/tennis/x"])
    assert got[0] == ["sport/#"]
    assert got[1] == sorted(["sport/#", "sport/+", "+/+"])
    assert got[2] == sorted(["a//c", "a/+/c"])
    assert got[3] == ["sport/#"]


def test_unknown_words_match_only_wildcards():
    idx, dev = build(["+/x", "#", "known/x"])
    got, _ = run_match(idx, dev, ["zzz/x", "zzz/zzz"])
    assert got[0] == sorted(["+/x", "#"])
    assert got[1] == ["#"]


@pytest.mark.parametrize("seed", [1, 2, 3])
def test_randomized_equivalence_vs_oracle(seed):
    rng = random.Random(seed)
    alphabet = ["a", "b", "c", "d", "e", ""]
    oracle = Trie()
    filters = set()
    for _ in range(600):
        ws = [rng.choice(alphabet + ["+", "#"]) for _ in range(rng.randint(1, 7))]
        if "#" in ws:
            ws = ws[: ws.index("#") + 1]
        f = T.join(ws)
        if T.validate_filter(f) and f not in filters:
            filters.add(f)
            oracle.insert(f)
    # exact-topic filters too (no wildcard)
    for _ in range(100):
        f = T.join(rng.choice(alphabet[:5]) for _ in range(rng.randint(1, 7)))
        if f not in filters:
            filters.add(f)
            oracle.insert(f)

    idx, dev = build(sorted(filters))
    topics = []
    for _ in range(256):
        nw = [rng.choice(alphabet[:5] + ["$x", "zz"]) for _ in range(rng.randint(1, 8))]
        topics.append(T.join(nw))

    got, overflow = run_match(idx, dev, topics, K=64)
    for b, topic in enumerate(topics):
        expect = sorted(oracle.match(topic))
        if overflow[b]:
            continue  # kernel reported incompleteness → host fallback
        assert got[b] == expect, (topic, got[b], expect)
    assert overflow.sum() < len(topics) // 4


def test_frontier_overflow_reported_not_wrong():
    """With tiny K the kernel must flag overflow rather than silently drop."""
    # '+' and exact branch points along one path grow the frontier
    filters = ["+/" * d + "#" for d in range(0, 7)] + ["a/" * d + "#" for d in range(0, 7)]
    filters = sorted(set(f for f in filters if T.validate_filter(f)))
    idx, dev = build(filters)
    oracle = Trie()
    for f in filters:
        oracle.insert(f)
    topics = ["a/a/a/a/a/a"]
    got, overflow = run_match(idx, dev, topics, K=2)
    if not overflow[0]:
        assert got[0] == sorted(oracle.match(topics[0]))


def test_deleted_filters_dont_match():
    idx = TrieIndex(max_levels=6)
    idx.load(["a/+", "a/#", "b/+"])
    idx.delete("a/#")
    dev = tm.device_trie(idx.ensure())
    got, _ = run_match(idx, dev, ["a/x"])
    assert got[0] == ["a/+"]
    # fid slot reuse: new filter takes the freed id
    fid = idx.insert("c/+")
    assert idx.filters[fid] == "c/+"
    dev = tm.device_trie(idx.ensure())
    got, _ = run_match(idx, dev, ["c/z", "a/x"])
    assert got[0] == ["c/+"]
    assert got[1] == ["a/+"]


def test_compact_fids():
    import jax.numpy as jnp

    cand = jnp.array([[-1, 5, -1, 3, -1], [7, -1, -1, -1, -1], [-1] * 5])
    packed, truncated = tm.compact_fids(cand, M=2)
    assert packed.tolist() == [[5, 3], [7, -1], [-1, -1]]
    assert truncated.tolist() == [False, False, False]


def test_match_counts():
    idx, dev = build(["a/+", "a/#", "#"])
    tokens, lengths, sys_flags, _ = idx.tokenize(["a/x", "q", "$S/x"])
    counts, overflow = tm.match_counts(
        dev, np.asarray(tokens), np.asarray(lengths), np.asarray(sys_flags)
    )
    assert counts.tolist() == [3, 1, 0]
