"""Gateway framework + 5 protocol gateways — mirrors the
emqx_gateway test suites (emqx_stomp_SUITE, emqx_sn_frame/protocol
SUITEs, emqx_coap_SUITE, emqx_lwm2m_SUITE, emqx_exproto_SUITE), driven
over real TCP/UDP sockets against a live BrokerApp."""

import asyncio
import json
import struct

import pytest

from emqx_tpu.app import BrokerApp
from emqx_tpu.gateway import coap as C
from emqx_tpu.gateway import mqttsn as SN
from emqx_tpu.gateway import stomp as ST
from emqx_tpu.gateway.coap import CoapMessage, Frame as CoapFrame
from emqx_tpu.gateway.exproto import (
    ConnectionHandler, ExprotoGateway, HandlerServer,
)
from emqx_tpu.gateway.lwm2m import Lwm2mGateway
from emqx_tpu.mqtt.client import MqttClient


def run(coro):
    asyncio.run(coro)


# -- stomp codec --------------------------------------------------------------

def test_stomp_frame_roundtrip():
    f = ST.Frame()
    frame = ST.StompFrame("SEND", {"destination": "a/b",
                                   "weird:h": "x\ny"}, b"hello")
    pkts, rest = f.parse(f.serialize(frame), b"")
    assert rest == b""
    assert pkts[0].command == "SEND"
    assert pkts[0].headers["destination"] == "a/b"
    assert pkts[0].headers["weird:h"] == "x\ny"
    assert pkts[0].body == b"hello"


def test_stomp_frame_partial_and_pipelined():
    f = ST.Frame()
    data = (f.serialize(ST.StompFrame("SEND", {"destination": "t"}, b"1"))
            + f.serialize(ST.StompFrame("SEND", {"destination": "t"}, b"2")))
    pkts1, st = f.parse(data[:10], b"")
    assert pkts1 == []
    pkts2, st = f.parse(data[10:], st)
    assert [p.body for p in pkts2] == [b"1", b"2"]


def test_stomp_frame_crlf_line_endings():
    f = ST.Frame()
    raw = b"SEND\r\ndestination:t\r\n\r\nhello\x00"
    pkts, rest = f.parse(raw, b"")
    assert rest == b""
    assert pkts[0].headers["destination"] == "t"
    assert pkts[0].body == b"hello"


def test_stomp_frame_content_length_allows_nul_in_body():
    f = ST.Frame()
    body = b"bin\x00ary"
    raw = (f"SEND\ndestination:t\ncontent-length:{len(body)}\n\n"
           .encode() + body + b"\x00")
    pkts, rest = f.parse(raw, b"")
    assert rest == b""
    assert pkts[0].body == body
    # incomplete content-length body buffers until complete
    pkts1, st = f.parse(raw[:-3], b"")
    assert pkts1 == []
    pkts2, _ = f.parse(raw[-3:], st)
    assert pkts2[0].body == body


def test_gateway_auth_denies_bad_credentials():
    """GwContext.authenticate must fail closed on authn error verdicts."""
    from emqx_tpu.access.authn import AuthnChain, BuiltinDbProvider
    from emqx_tpu.access.control import AccessControl

    p = BuiltinDbProvider()
    p.add_user("alice", "secret")
    app = BrokerApp(access_control=AccessControl(authn=AuthnChain([p])))
    from emqx_tpu.gateway.ctx import GwContext
    ctx = GwContext(app, "test")
    assert ctx.authenticate("c1", username="alice", password="secret")
    assert not ctx.authenticate("c1", username="alice", password="wrong")
    assert not ctx.authenticate("c1", username="nobody", password="x")


def test_udp_gateway_expires_idle_channels():
    async def main():
        app = BrokerApp()
        gw = app.gateway.load(SN.MqttsnGateway(port=0))
        await gw.start_listeners()
        gw.listener.idle_timeout_s = 0.01
        dev = SnClient(gw.port)
        await dev.start()
        dev.send(SN.SnMessage(SN.CONNECT, clientid="sleepy"))
        await dev.recv()
        assert len(gw.listener.channels) == 1
        assert app.cm.lookup_channel("sleepy") is not None
        await asyncio.sleep(0.05)
        assert gw.listener.expire_idle() == 1
        assert gw.listener.channels == {}
        assert app.cm.lookup_channel("sleepy") is None
        await gw.stop_listeners()

    run(main())


# -- stomp end-to-end over TCP ------------------------------------------------

class StompClient:
    def __init__(self, port):
        self.port = port
        self.f = ST.Frame()
        self.state = b""
        self.pending = []

    async def connect(self):
        self.r, self.w = await asyncio.open_connection("127.0.0.1",
                                                       self.port)

    async def send(self, cmd, headers=None, body=b""):
        self.w.write(self.f.serialize(ST.StompFrame(cmd, headers or {},
                                                    body)))
        await self.w.drain()

    async def recv(self, timeout=5.0):
        while not self.pending:
            data = await asyncio.wait_for(self.r.read(4096), timeout)
            assert data, "connection closed"
            pkts, self.state = self.f.parse(data, self.state)
            self.pending.extend(pkts)
        return self.pending.pop(0)


def test_stomp_pubsub_and_mqtt_interop():
    async def main():
        app = BrokerApp()
        gw = app.gateway.load(ST.StompGateway(port=0))
        await gw.start_listeners()
        from emqx_tpu.broker.server import BrokerServer
        srv = BrokerServer(port=0, app=app)
        await srv.start()

        c1 = StompClient(gw.port)
        await c1.connect()
        await c1.send("CONNECT", {"accept-version": "1.2",
                                  "login": "alice"})
        connected = await c1.recv()
        assert connected.command == "CONNECTED"
        await c1.send("SUBSCRIBE", {"id": "0", "destination": "cars/+"})
        # an MQTT client publishes; the STOMP side must receive
        mq = MqttClient(port=srv.port, clientid="m1")
        await mq.connect()
        await mq.publish("cars/tesla", b"vroom")
        msg = await c1.recv()
        assert msg.command == "MESSAGE"
        assert msg.headers["destination"] == "cars/tesla"
        assert msg.headers["subscription"] == "0"
        assert msg.body == b"vroom"
        # STOMP SEND reaches MQTT subscribers
        await mq.subscribe("stomp/#")
        await c1.send("SEND", {"destination": "stomp/out",
                               "receipt": "r1"}, b"from-stomp")
        rec = await c1.recv()
        assert rec.command == "RECEIPT"
        assert rec.headers["receipt-id"] == "r1"
        got = await mq.recv()
        assert got.topic == "stomp/out" and got.payload == b"from-stomp"
        await mq.close()
        await gw.stop_listeners()
        await srv.stop()

    run(main())


# -- mqtt-sn codec -------------------------------------------------------------

def test_sn_frame_roundtrip():
    f = SN.Frame()
    m = SN.SnMessage(SN.PUBLISH, flags=SN.qos_flags(1), topic_id=7,
                     msg_id=42, data=b"xyz")
    pkts, _ = f.parse(f.serialize(m), None)
    p = pkts[0]
    assert (p.type, p.topic_id, p.msg_id, p.data) == (SN.PUBLISH, 7, 42,
                                                      b"xyz")
    assert SN.qos_of(p.flags) == 1


def test_sn_connect_roundtrip():
    f = SN.Frame()
    m = SN.SnMessage(SN.CONNECT, flags=SN.F_CLEAN, duration=30,
                     clientid="dev1")
    p = f.parse(f.serialize(m), None)[0][0]
    assert p.clientid == "dev1" and p.duration == 30


class SnClient:
    def __init__(self, port):
        self.f = SN.Frame()
        self.port = port

    async def start(self):
        loop = asyncio.get_running_loop()
        self.q = asyncio.Queue()
        cli = self

        class Proto(asyncio.DatagramProtocol):
            def datagram_received(self, data, addr):
                for m in cli.f.parse(data, None)[0]:
                    cli.q.put_nowait(m)

        self.tr, _ = await loop.create_datagram_endpoint(
            Proto, remote_addr=("127.0.0.1", self.port))

    def send(self, m):
        self.tr.sendto(self.f.serialize(m))

    async def recv(self, timeout=5.0):
        return await asyncio.wait_for(self.q.get(), timeout)


def test_mqttsn_register_publish_subscribe():
    async def main():
        app = BrokerApp()
        gw = app.gateway.load(SN.MqttsnGateway(port=0),
                              {"predefined": {1: "pre/defined"}})
        await gw.start_listeners()

        dev = SnClient(gw.port)
        await dev.start()
        dev.send(SN.SnMessage(SN.CONNECT, clientid="sn-dev"))
        assert (await dev.recv()).rc == SN.RC_ACCEPTED
        # register + publish qos1
        dev.send(SN.SnMessage(SN.REGISTER, msg_id=1,
                              topic_name="sensors/t1"))
        regack = await dev.recv()
        tid = regack.topic_id
        assert regack.rc == SN.RC_ACCEPTED and tid > 0
        # subscribe by name (another device), then publish by id
        dev2 = SnClient(gw.port)
        await dev2.start()
        dev2.send(SN.SnMessage(SN.CONNECT, clientid="sn-dev2"))
        await dev2.recv()
        dev2.send(SN.SnMessage(SN.SUBSCRIBE, flags=SN.qos_flags(0),
                               msg_id=2, topic_name="sensors/#"))
        suback = await dev2.recv()
        assert suback.type == SN.SUBACK and suback.rc == SN.RC_ACCEPTED
        dev.send(SN.SnMessage(SN.PUBLISH, flags=SN.qos_flags(1),
                              topic_id=tid, msg_id=3, data=b"21.5"))
        puback = await dev.recv()
        assert puback.type == SN.PUBACK and puback.rc == SN.RC_ACCEPTED
        # dev2 gets auto-REGISTER then PUBLISH
        reg = await dev2.recv()
        assert reg.type == SN.REGISTER and reg.topic_name == "sensors/t1"
        pub = await dev2.recv()
        assert pub.type == SN.PUBLISH and pub.data == b"21.5"
        assert pub.topic_id == reg.topic_id
        await gw.stop_listeners()

    run(main())


def test_mqttsn_qos_minus_one_predefined():
    async def main():
        app = BrokerApp()
        gw = app.gateway.load(SN.MqttsnGateway(port=0),
                              {"predefined": {1: "pre/defined"}})
        await gw.start_listeners()
        seen = []
        app.hooks.add("message.publish",
                      lambda m: seen.append((m.topic, m.payload)) or None,
                      priority=-500)
        dev = SnClient(gw.port)
        await dev.start()
        # QoS -1 publish without CONNECT on predefined topic id 1
        dev.send(SN.SnMessage(SN.PUBLISH, flags=SN.qos_flags(-1) | 0x1,
                              topic_id=1, data=b"fire"))
        await asyncio.sleep(0.1)
        assert ("pre/defined", b"fire") in seen
        await gw.stop_listeners()

    run(main())


# -- coap codec ----------------------------------------------------------------

def test_coap_codec_roundtrip_with_extended_options():
    f = CoapFrame()
    m = CoapMessage(C.CON, C.GET, 0x1234, b"tok1",
                    [(C.OPT_URI_PATH, b"ps"), (C.OPT_URI_PATH, b"a"),
                     (C.OPT_OBSERVE, b"\x00"),
                     (2000, b"x" * 300)],       # forces 14-extensions
                    b"payload")
    out = f.parse(f.serialize(m), None)[0][0]
    assert out.code == C.GET and out.mid == 0x1234
    assert out.token == b"tok1"
    assert out.uri_path() == ["ps", "a"]
    assert out.opt(2000) == b"x" * 300
    assert out.payload == b"payload"


class CoapClient:
    def __init__(self, port):
        self.f = CoapFrame()
        self.port = port
        self._mid = 0

    async def start(self):
        loop = asyncio.get_running_loop()
        self.q = asyncio.Queue()
        cli = self

        class Proto(asyncio.DatagramProtocol):
            def datagram_received(self, data, addr):
                for m in cli.f.parse(data, None)[0]:
                    cli.q.put_nowait(m)

        self.tr, _ = await loop.create_datagram_endpoint(
            Proto, remote_addr=("127.0.0.1", self.port))

    def request(self, code, path, payload=b"", options=(), token=b"t",
                queries=()):
        self._mid += 1
        opts = list(options) + C.uri_path_opts(path)
        for q in queries:
            opts.append((C.OPT_URI_QUERY, q.encode()))
        self.tr.sendto(self.f.serialize(CoapMessage(
            C.CON, code, self._mid, token, opts, payload)))

    async def recv(self, timeout=5.0):
        return await asyncio.wait_for(self.q.get(), timeout)


def test_coap_pubsub_observe():
    async def main():
        app = BrokerApp()
        gw = app.gateway.load(C.CoapGateway(port=0))
        await gw.start_listeners()

        sub = CoapClient(gw.port)
        await sub.start()
        sub.request(C.GET, "ps/room/temp", token=b"obs1",
                    options=[(C.OPT_OBSERVE, b"")],
                    queries=["clientid=c-sub"])
        ack = await sub.recv()
        assert ack.code == C.CONTENT

        pub = CoapClient(gw.port)
        await pub.start()
        pub.request(C.PUT, "ps/room/temp", payload=b"21",
                    queries=["clientid=c-pub"])
        ack2 = await pub.recv()
        assert ack2.code == C.CHANGED

        notify = await sub.recv()
        assert notify.code == C.CONTENT and notify.payload == b"21"
        assert notify.token == b"obs1"
        assert notify.opt(C.OPT_OBSERVE) is not None
        await gw.stop_listeners()

    run(main())


# -- lwm2m ---------------------------------------------------------------------

def test_lwm2m_register_update_uplink_downlink():
    async def main():
        app = BrokerApp()
        gw = app.gateway.load(Lwm2mGateway(port=0))
        await gw.start_listeners()
        uplinks = []
        app.hooks.add(
            "message.publish",
            lambda m: uplinks.append((m.topic, m.payload)) or None,
            priority=-500)

        dev = CoapClient(gw.port)
        await dev.start()
        dev.request(C.POST, "rd", payload=b"</1/0>,</3/0>",
                    queries=["ep=ep-1", "lt=120", "lwm2m=1.0"])
        created = await dev.recv()
        assert created.code == C.CREATED
        loc = [v.decode() for v in created.opts(C.OPT_LOCATION_PATH)]
        assert loc[0] == "rd" and len(loc) == 2
        assert any(t == "lwm2m/ep-1/up/register" for t, _ in uplinks)
        reg = json.loads([p for t, p in uplinks
                          if t == "lwm2m/ep-1/up/register"][0])
        assert reg["lt"] == 120
        assert {o["path"] for o in reg["objects"]} == {"/1/0", "/3/0"}

        # update
        dev.request(C.POST, f"rd/{loc[1]}", queries=["lt=300"])
        assert (await dev.recv()).code == C.CHANGED

        # downlink: publish a command to the device's dn topic
        from emqx_tpu.core.message import Message
        app.cm.dispatch(app.broker.publish(Message(
            topic="lwm2m/ep-1/dn/read", payload=b'{"path":"/3/0/0"}')))
        cmd = await dev.recv()
        assert cmd.code == C.POST
        assert cmd.uri_path() == ["dn", "read"]
        assert cmd.payload == b'{"path":"/3/0/0"}'
        await gw.stop_listeners()

    run(main())


# -- exproto -------------------------------------------------------------------

class EchoLineProtocol(ConnectionHandler):
    """A toy external protocol: 'AUTH <id>' authenticates, 'SUB <t>'
    subscribes, 'PUB <t> <msg>' publishes, deliveries are sent back as
    'MSG <t> <payload>' lines."""

    def on_received_bytes(self, args):
        line = bytes.fromhex(args["bytes_hex"]).decode().strip()
        verb, _, rest = line.partition(" ")
        if verb == "AUTH":
            return [{"type": "authenticate", "clientid": rest},
                    {"type": "send", "bytes_hex": b"OK\n".hex()}]
        if verb == "SUB":
            return [{"type": "subscribe", "topic": rest, "qos": 0},
                    {"type": "send", "bytes_hex": b"OK\n".hex()}]
        if verb == "PUB":
            t, _, payload = rest.partition(" ")
            return [{"type": "publish", "topic": t,
                     "payload_hex": payload.encode().hex()}]
        return [{"type": "send", "bytes_hex": b"ERR\n".hex()}]

    def on_received_messages(self, args):
        out = []
        for m in args["messages"]:
            line = (f"MSG {m['topic']} "
                    + bytes.fromhex(m["payload_hex"]).decode() + "\n")
            out.append({"type": "send", "bytes_hex": line.encode().hex()})
        return out


def test_exproto_external_protocol_bridges_to_broker():
    async def main():
        handler = HandlerServer(EchoLineProtocol())
        handler.start()
        app = BrokerApp()
        gw = app.gateway.load(ExprotoGateway(
            handler_port=handler.port, port=0))
        await gw.start_listeners()
        from emqx_tpu.broker.server import BrokerServer
        srv = BrokerServer(port=0, app=app)
        await srv.start()

        r, w = await asyncio.open_connection("127.0.0.1", gw.port)
        w.write(b"AUTH dev-9\n")
        assert await r.readline() == b"OK\n"
        w.write(b"SUB alerts/#\n")
        assert await r.readline() == b"OK\n"

        mq = MqttClient(port=srv.port, clientid="m1")
        await mq.connect()
        await mq.subscribe("from-device/#")
        # device → broker
        w.write(b"PUB from-device/d9 ping\n")
        got = await mq.recv()
        assert got.topic == "from-device/d9" and got.payload == b"ping"
        # broker → device
        await mq.publish("alerts/red", b"evacuate")
        line = await asyncio.wait_for(r.readline(), 5)
        assert line == b"MSG alerts/red evacuate\n"

        w.close()
        await mq.close()
        await gw.stop_listeners()
        await srv.stop()
        handler.stop()

    run(main())


# -- manager -------------------------------------------------------------------

def test_gateway_manager_load_unload_and_mountpoint():
    async def main():
        app = BrokerApp()
        gw = app.gateway.load(ST.StompGateway(port=0),
                              {"mountpoint": "stomp/"})
        await gw.start_listeners()
        (row,) = app.gateway.list()
        assert row["name"] == "stomp" and row["status"] == "running"
        assert row["mountpoint"] == "stomp/" and row["port"] == gw.port
        c = StompClient(gw.port)
        await c.connect()
        await c.send("CONNECT", {"accept-version": "1.2"})
        await c.recv()
        await c.send("SUBSCRIBE", {"id": "0", "destination": "x"})
        await asyncio.sleep(0.05)
        assert any(t == "stomp/x" for t in app.broker.subscriber)
        await gw.stop_listeners()
        assert app.gateway.unload("stomp")
        assert not app.gateway.unload("stomp")

    run(main())


# -- review-fix regressions ----------------------------------------------------

def test_mqttsn_frame_malformed_length_does_not_loop():
    f = SN.Frame()
    # zero/one length octets and a truncated 3-byte-prefix header must
    # terminate parsing instead of spinning forever
    for bad in (b"\x00", b"\x01", b"\x01\x00", b"\x01\x00\x00",
                b"\x01\x00\x02\x00"):
        pkts, _ = f.parse(bad, None)
        assert pkts == []


def test_mqttsn_sleep_mode_buffers_until_pingreq():
    from emqx_tpu.gateway.ctx import GwContext

    app = BrokerApp()
    ch = SN.Channel(GwContext(app, "mqttsn"), SN.Registry())
    assert ch.handle_in(SN.SnMessage(SN.CONNECT, clientid="dev1"))[0].rc == 0
    # enter sleep
    out = ch.handle_in(SN.SnMessage(SN.DISCONNECT, duration=60))
    assert out[0].type == SN.DISCONNECT and not ch.awake
    from emqx_tpu.core.message import Message
    delivered = ch.handle_deliver(
        [("t", Message(topic="t", payload=b"zzz", qos=0))])
    assert delivered == []                       # parked, not sent
    woke = ch.handle_in(SN.SnMessage(SN.PINGREQ))
    kinds = [m.type for m in woke]
    assert kinds[-1] == SN.PINGRESP
    assert SN.PUBLISH in kinds                   # parked message flushed


def test_gateway_ctx_runs_authorize_hook():
    from emqx_tpu.broker.hooks import Hooks
    from emqx_tpu.gateway.ctx import GwContext

    app = BrokerApp()
    app.hooks.add(
        "client.authorize",
        lambda ci, action, topic, acc:
            (Hooks.STOP, "deny") if topic.startswith("secret/") else None,
        priority=2000)      # outrank the AccessControl chain terminator
    ctx = GwContext(app, "test")
    assert ctx.publish("c1", "ok/topic", b"x") is True
    assert ctx.publish("c1", "secret/topic", b"x") is False
    assert ctx.subscribe("c1", "secret/#") is False
    assert ctx.subscribe("c1", "ok/#") is True


def test_lwm2m_notify_requires_registration():
    from emqx_tpu.gateway.ctx import GwContext
    from emqx_tpu.gateway.lwm2m import Channel as LwChannel, NOT_FOUND, POST

    app = BrokerApp()
    ch = LwChannel(GwContext(app, "lwm2m"))
    m = CoapMessage(0, POST, 1, b"", [(11, b"rd"), (11, b"999"),
                                      (11, b"notify")], b"{}")
    out = ch.handle_in(m)
    assert out[0].code == NOT_FOUND              # unregistered → rejected


def test_gateway_unload_stops_listeners():
    async def main():
        app = BrokerApp()
        gw = app.gateway.load(ST.StompGateway(port=0))
        await gw.start_listeners()
        port = gw.port
        assert app.gateway.unload("stomp") is True
        await asyncio.sleep(0.05)                # scheduled teardown runs
        with pytest.raises(OSError):
            r, w = await asyncio.open_connection("127.0.0.1", port)
            # if something still accepts, fail loudly
            w.close()
    run(main())


# -- coap transport machine (emqx_coap_tm) -------------------------------------

def test_coap_tm_dedup_replays_cached_response():
    """A retransmitted CON request must get the SAME cached reply without
    re-executing (a duplicated PUT must not publish twice)."""
    async def main():
        app = BrokerApp()
        published = []
        app.hooks.add("message.publish",
                      lambda m: published.append(m.topic) or None,
                      priority=-500)
        gw = app.gateway.load(C.CoapGateway(port=0))
        await gw.start_listeners()
        cli = CoapClient(gw.port)
        await cli.start()
        cli.request(C.PUT, "ps/dup/t", payload=b"once",
                    queries=["clientid=c-dup"])
        a1 = await cli.recv()
        # retransmit the SAME mid (simulated lost ACK)
        cli._mid -= 1
        cli.request(C.PUT, "ps/dup/t", payload=b"once",
                    queries=["clientid=c-dup"])
        a2 = await cli.recv()
        assert (a1.code, a1.mid) == (a2.code, a2.mid) == (C.CHANGED, 1)
        assert published.count("dup/t") == 1, "duplicate CON re-executed"
        await gw.stop_listeners()
    run(main())


def test_coap_qos1_notify_is_con_and_retransmits():
    """QoS1 observers get CON notifications; an unacked CON retransmits
    with backoff and finally cancels the observation."""
    from emqx_tpu.gateway.coap import TransportManager

    async def main():
        app = BrokerApp()
        gw = app.gateway.load(C.CoapGateway(port=0))
        await gw.start_listeners()
        sub = CoapClient(gw.port)
        await sub.start()
        sub.request(C.GET, "ps/alarm/#", token=b"ob2",
                    options=[(C.OPT_OBSERVE, b"")],
                    queries=["clientid=c-q1", "qos=1"])
        await sub.recv()

        pub = CoapClient(gw.port)
        await pub.start()
        pub.request(C.PUT, "ps/alarm/fire", payload=b"!",
                    queries=["clientid=c-p2"])
        await pub.recv()
        notify = await sub.recv()
        assert notify.type == C.CON, "qos1 notify must be confirmable"
        (addr, ch), = [(a, c) for a, c in gw.listener.channels.items()
                       if c.observers]
        assert ch.tm.pending_count() == 1

        # no ACK ever: force the clock forward through every retransmit
        import time as _t
        t = [_t.monotonic()]
        ch.tm.now = lambda: t[0]
        total = 0
        for i in range(C.MAX_RETRANSMIT + 1):
            t[0] += 200.0
            retx, gave_up = ch.tm.tick()
            total += len(retx)
        assert total == C.MAX_RETRANSMIT
        assert gave_up == [notify.mid]
        # channel housekeep on give-up cancels the dead observer
        ch._con_topic[notify.mid] = "alarm/#"
        ch.tm._pending[notify.mid] = [notify, C.MAX_RETRANSMIT, 0.0, 1.0]
        ch.housekeep()
        assert "alarm/#" not in ch.observers
        await gw.stop_listeners()
    run(main())


def test_coap_ack_settles_con_and_rst_cancels_observe():
    async def main():
        app = BrokerApp()
        gw = app.gateway.load(C.CoapGateway(port=0))
        await gw.start_listeners()
        sub = CoapClient(gw.port)
        await sub.start()
        sub.request(C.GET, "ps/st/1", token=b"ob3",
                    options=[(C.OPT_OBSERVE, b"")],
                    queries=["clientid=c-q2", "qos=1"])
        await sub.recv()
        pub = CoapClient(gw.port)
        await pub.start()
        pub.request(C.PUT, "ps/st/1", payload=b"x",
                    queries=["clientid=c-p3"])
        await pub.recv()
        notify = await sub.recv()
        (_, ch), = [(a, c) for a, c in gw.listener.channels.items()
                    if c.observers]
        # client ACKs the notify → pending settles
        sub.tr.sendto(sub.f.serialize(CoapMessage(
            C.ACK, C.EMPTY, notify.mid, b"")))
        await asyncio.sleep(0.2)
        assert ch.tm.pending_count() == 0
        assert "st/1" in ch.observers

        # next notify answered by RST → observation cancelled (RFC 7641)
        pub.request(C.PUT, "ps/st/1", payload=b"y",
                    queries=["clientid=c-p3"])
        await pub.recv()
        n2 = await sub.recv()
        sub.tr.sendto(sub.f.serialize(CoapMessage(
            C.RST, C.EMPTY, n2.mid, b"")))
        await asyncio.sleep(0.2)
        assert "st/1" not in ch.observers
        await gw.stop_listeners()
    run(main())


# -- lwm2m object registry -----------------------------------------------------

def test_lwm2m_object_registry_lookup_and_paths():
    from emqx_tpu.gateway import lwm2m_objects as O

    dev = O.object_by_id(3)
    assert dev.name == "Device" and dev.urn.endswith(":3")
    assert O.object_by_name("Firmware Update").oid == 5
    assert dev.resource(0).name == "Manufacturer"
    assert dev.resource(4).operations == "E"
    assert O.translate_path("/3/0/0") == "Device/0/Manufacturer"
    assert O.translate_path("/6/0/1") == "Location/0/Longitude"
    assert O.translate_path("/99/0/1") is None
    assert O.parse_path("/3/0") == (3, 0, None)
    assert O.parse_path("/bogus") == (None, None, None)
    # operation validation
    assert O.check_operation("/3/0/0", "R")          # Manufacturer: R
    assert not O.check_operation("/3/0/0", "W")
    assert O.check_operation("/3/0/4", "E")          # Reboot: E
    assert not O.check_operation("/3/0/4", "R")
    assert O.check_operation("/5/0/1", "W")          # Package URI: W
    assert O.check_operation("/3/0", "R")            # instance read ok
    assert O.check_operation("/99/1/2", "R")         # vendor obj: forward
    links = O.parse_core_links('</3/0>,</5>;ver=1.0,</31024/11>')
    assert links[0] == {"path": "/3/0", "oid": 3, "instance": 0,
                        "name": "Device"}
    assert links[1]["name"] == "Firmware Update"
    assert links[2]["name"] is None                  # vendor object


def test_lwm2m_register_resolves_objects_and_validates_downlink():
    async def main():
        app = BrokerApp()
        gw = app.gateway.load(Lwm2mGateway(port=0))
        await gw.start_listeners()
        uplinks = []
        app.hooks.add(
            "message.publish",
            lambda m: uplinks.append((m.topic, m.payload)) or None,
            priority=-500)
        cli = CoapClient(gw.port)
        await cli.start()
        cli.request(C.POST, "rd", payload=b"</3/0>,</5/0>",
                    queries=["ep=dev9", "lt=120", "lwm2m=1.0"])
        ack = await cli.recv()
        assert ack.code == C.CREATED
        reg = json.loads(dict(uplinks)["lwm2m/dev9/up/register"])
        assert {o["name"] for o in reg["objects"]} == \
            {"Device", "Firmware Update"}

        # downlink: write to a read-only resource → uplink 4.05 response,
        # nothing sent to the device
        from emqx_tpu.core.message import Message
        app.cm.dispatch(app.broker.publish(Message(
            topic="lwm2m/dev9/dn/cmd",
            payload=json.dumps({
                "reqID": 7, "msgType": "write",
                "data": {"path": "/3/0/0", "value": "x"}}).encode())))
        await asyncio.sleep(0.2)
        resp = json.loads(dict(uplinks)["lwm2m/dev9/up/response"])
        assert resp["data"]["code"] == "4.05"
        assert resp["data"]["name"] == "Device/0/Manufacturer"
        assert resp["reqID"] == 7
        await gw.stop_listeners()
    run(main())


def test_coap_ping_gets_rst_pong_and_does_not_settle_notifies():
    """CON+EMPTY is a CoAP ping (RFC 7252 §4.3): answer RST, and never
    treat the client's mid as an ACK of OUR pending notify."""
    from emqx_tpu.gateway.coap import Channel as CoapChannel, TransportManager

    async def main():
        app = BrokerApp()
        gw = app.gateway.load(C.CoapGateway(port=0))
        await gw.start_listeners()
        cli = CoapClient(gw.port)
        await cli.start()
        cli.tr.sendto(cli.f.serialize(CoapMessage(C.CON, C.EMPTY, 42, b"")))
        pong = await cli.recv()
        assert (pong.type, pong.code, pong.mid) == (C.RST, C.EMPTY, 42)
        await gw.stop_listeners()

    run(main())
    # unit: ping mid colliding with a pending CON must not settle it
    ch = CoapChannel.__new__(CoapChannel)
    ch.tm = TransportManager()
    ch._con_topic = {}
    ch.observers = {}
    pending = CoapMessage(C.CON, C.CONTENT, 7, b"tk")
    ch.tm.track(pending)
    out = CoapChannel.handle_in(ch, CoapMessage(C.CON, C.EMPTY, 7, b""))
    assert out[0].type == C.RST
    assert ch.tm.pending_count() == 1, "ping settled a pending notify"


def test_lwm2m_duplicate_register_is_deduped():
    """A retransmitted CON POST /rd (lost ACK) must replay the cached
    2.01 — not re-register and double-publish the register uplink."""
    async def main():
        app = BrokerApp()
        gw = app.gateway.load(Lwm2mGateway(port=0))
        await gw.start_listeners()
        uplinks = []
        app.hooks.add("message.publish",
                      lambda m: uplinks.append(m.topic) or None,
                      priority=-500)
        cli = CoapClient(gw.port)
        await cli.start()
        for _ in range(2):                  # original + retransmission
            cli._mid = 5
            cli.request(C.POST, "rd", payload=b"</3/0>",
                        queries=["ep=dup-ep", "lt=60"])
            ack = await cli.recv()
            assert ack.code == C.CREATED
        assert uplinks.count("lwm2m/dup-ep/up/register") == 1
        await gw.stop_listeners()
    run(main())


def test_vendor_object_commands_are_forwarded():
    from emqx_tpu.gateway import lwm2m_objects as O

    assert O.check_operation("/31024/11/0", "W")     # vendor: forward
    assert not O.check_operation("/not-a-path", "R")
    assert O.parse_path("/--1/0") == (None, None, None)
    # write-attr allowed on readable resources
    assert O.check_operation("/3/0/9", "R")


def test_lwm2m_device_response_and_timeout_uplinks():
    """A device ACK carrying a result becomes an up/response; an
    unresponsive device surfaces a 5.04 timeout uplink."""
    async def main():
        app = BrokerApp()
        gw = app.gateway.load(Lwm2mGateway(port=0))
        await gw.start_listeners()
        uplinks = []
        app.hooks.add("message.publish",
                      lambda m: uplinks.append((m.topic, m.payload)) or None,
                      priority=-500)
        cli = CoapClient(gw.port)
        await cli.start()
        cli.request(C.POST, "rd", payload=b"</3/0>", queries=["ep=rsp-ep"])
        await cli.recv()
        (ch,) = [c for c in gw.listener.channels.values()
                 if getattr(c, "endpoint", None) == "rsp-ep"]
        # downlink read → device CON POST
        from emqx_tpu.core.message import Message
        app.cm.dispatch(app.broker.publish(Message(
            topic="lwm2m/rsp-ep/dn/cmd",
            payload=json.dumps({"reqID": 1, "msgType": "read",
                                "data": {"path": "/3/0/0"}}).encode())))
        cmd = await cli.recv()
        assert cmd.type == C.CON
        # device answers with piggybacked 2.05 + value
        cli.tr.sendto(cli.f.serialize(CoapMessage(
            C.ACK, C.CONTENT, cmd.mid, cmd.token, [], b"ACME Corp")))
        await asyncio.sleep(0.2)
        resp = json.loads(dict(uplinks)["lwm2m/rsp-ep/up/response"])
        assert resp["data"]["code"] == "2.05"
        assert resp["data"]["content"] == "ACME Corp"

        # second command never ACKed → timeout uplink on give-up
        uplinks.clear()
        app.cm.dispatch(app.broker.publish(Message(
            topic="lwm2m/rsp-ep/dn/cmd2",
            payload=json.dumps({"reqID": 2, "msgType": "read",
                                "data": {"path": "/3/0/1"}}).encode())))
        await cli.recv()
        for st in ch.tm._pending.values():
            st[1] = C.MAX_RETRANSMIT       # exhaust retries
            st[2] = 0.0
        ch.housekeep()
        resp = json.loads(dict(uplinks)["lwm2m/rsp-ep/up/response"])
        assert resp["data"]["code"] == "5.04"
        await gw.stop_listeners()
    run(main())


def test_coap_rst_on_non_notify_cancels_observe():
    """RFC 7641 §3.6: RST answering ANY notification (CON or NON)
    deregisters the observer."""
    async def main():
        app = BrokerApp()
        gw = app.gateway.load(C.CoapGateway(port=0))
        await gw.start_listeners()
        cli = CoapClient(gw.port)
        await cli.start()
        cli.request(C.GET, "ps/n0/t", token=b"ob0",
                    options=[(C.OPT_OBSERVE, b"")],
                    queries=["clientid=c-n0"])       # qos0 → NON notifies
        await cli.recv()
        from emqx_tpu.core.message import Message
        app.cm.dispatch(app.broker.publish(
            Message(topic="n0/t", payload=b"v1")))
        note = await cli.recv()
        assert note.type == C.NON
        (ch,) = [c for c in gw.listener.channels.values() if c.observers]
        cli.tr.sendto(cli.f.serialize(CoapMessage(
            C.RST, C.EMPTY, note.mid, b"")))
        await asyncio.sleep(0.2)
        assert not ch.observers, "RST on NON notify must cancel observe"
        await gw.stop_listeners()
    run(main())


# -- coap blockwise (RFC 7959) -------------------------------------------------

def test_coap_block_option_codec():
    import pytest as _p

    from emqx_tpu.gateway.coap import encode_block, parse_block
    for num, more, size in ((0, 1, 16), (3, 0, 64), (1000, 1, 1024),
                            (0, 0, 16)):
        assert parse_block(encode_block(num, more, size)) == \
            (num, more, size)
    with _p.raises(ValueError):          # SZX 7 reserved (BERT)
        parse_block(b"\x0f")


def test_coap_block1_upload_reassembles():
    """A 3-block PUT publish: 2.31 Continue per intermediate block, the
    reassembled payload reaches an MQTT subscriber; out-of-order blocks
    answer 4.08."""
    async def main():
        app = BrokerApp()
        gw = app.gateway.load(C.CoapGateway(port=0))
        await gw.start_listeners()
        from emqx_tpu.broker.server import BrokerServer
        srv = BrokerServer(port=0, app=app)
        await srv.start()
        mq = MqttClient(port=srv.port, clientid="m1")
        await mq.connect()
        await mq.subscribe("up/big")

        dev = CoapClient(gw.port)
        await dev.start()
        parts = [b"A" * 16, b"B" * 16, b"C" * 5]
        for i, part in enumerate(parts):
            more = 1 if i < len(parts) - 1 else 0
            dev.request(C.PUT, "ps/up/big", payload=part,
                        options=[(C.OPT_BLOCK1,
                                  C.encode_block(i, more, 16))],
                        queries=["clientid=c-dev"])
            resp = await dev.recv()
            want = C.CONTINUE_231 if more else C.CHANGED
            assert resp.code == want, hex(resp.code)
        got = await mq.recv()
        assert got.payload == b"".join(parts)

        # out-of-order: block 2 with no transfer in progress → 4.08
        dev.request(C.PUT, "ps/up/big", payload=b"x",
                    options=[(C.OPT_BLOCK1, C.encode_block(2, 1, 16))],
                    queries=["clientid=c-dev"])
        resp = await dev.recv()
        assert resp.code == C.REQUEST_ENTITY_INCOMPLETE

        await mq.disconnect()
        await srv.stop()
        await gw.stop_listeners()

    run(main())


def test_coap_block2_download_slices_retained():
    """Reading a large retained message: the response auto-slices past
    the threshold and subsequent Block2 GETs walk the blocks."""
    async def main():
        app = BrokerApp()
        from emqx_tpu.core.message import Message
        body = bytes(range(256)) * 10            # 2560 bytes > 1024
        app.retainer.store(Message(topic="cfg/blob", payload=body,
                                   flags={"retain": True}))
        gw = app.gateway.load(C.CoapGateway(port=0))
        await gw.start_listeners()
        dev = CoapClient(gw.port)
        await dev.start()

        got = bytearray()
        num = 0
        etags = set()
        while True:
            opts = ([(C.OPT_BLOCK2, C.encode_block(num, 0, 1024))]
                    if num else [])
            dev.request(C.GET, "ps/cfg/blob", options=opts,
                        queries=["clientid=c-r"])
            resp = await dev.recv()
            assert resp.code == C.CONTENT
            bnum, more, size = C.parse_block(resp.opt(C.OPT_BLOCK2))
            assert bnum == num and size == 1024
            # Size2 announces the total; the ETag is stable across
            # blocks of one representation (torn-read detection, §2.4)
            assert int.from_bytes(resp.opt(C.OPT_SIZE2), "big") == \
                len(body)
            etags.add(resp.opt(C.OPT_ETAG))
            got += resp.payload
            if not more:
                break
            num += 1
        assert bytes(got) == body
        assert len(etags) == 1 and next(iter(etags))
        await gw.stop_listeners()

    run(main())


# -- lwm2m TLV content codec (emqx_lwm2m_tlv + emqx_lwm2m_message) -------------

def test_lwm2m_tlv_structural_roundtrip():
    from emqx_tpu.gateway import lwm2m_tlv as TLV
    entries = [
        {"kind": TLV.OBJ_INSTANCE, "id": 0, "children": [
            {"kind": TLV.RESOURCE, "id": 0, "value": b"ACME"},
            {"kind": TLV.RESOURCE, "id": 9, "value": b"\x55"},
            {"kind": TLV.MULTI_RES, "id": 6, "children": [
                {"kind": TLV.RES_INSTANCE, "id": 0, "value": b"\x01"},
                {"kind": TLV.RES_INSTANCE, "id": 1, "value": b"\x05"},
            ]},
        ]},
        {"kind": TLV.RESOURCE, "id": 300, "value": b"x" * 300},  # 16-bit
    ]
    assert TLV.tlv_decode(TLV.tlv_encode(entries)) == entries
    import pytest as _p
    with _p.raises(TLV.TlvError):
        TLV.tlv_decode(b"\xc0")                  # truncated identifier


def test_lwm2m_tlv_typed_values():
    from emqx_tpu.gateway import lwm2m_tlv as TLV
    for value, rtype in ((42, "Integer"), (-7, "Integer"),
                         (1 << 40, "Integer"), (3.5, "Float"),
                         (True, "Boolean"), (False, "Boolean"),
                         ("hello", "String"), ("deadbeef", "Opaque"),
                         (1700000000, "Time"), ("3:0", "Objlnk")):
        raw = TLV.encode_value(value, rtype)
        assert TLV.decode_value(raw, rtype) == value, (value, rtype)


def test_lwm2m_tlv_path_values_device_object():
    """A Read /3/0 TLV response decodes to named, typed rows via the
    object registry (Device: 0=Manufacturer String, 9=Battery Integer)."""
    from emqx_tpu.gateway import lwm2m_tlv as TLV
    body = TLV.tlv_encode([
        {"kind": TLV.OBJ_INSTANCE, "id": 0, "children": [
            {"kind": TLV.RESOURCE, "id": 0, "value": b"ACME"},
            {"kind": TLV.RESOURCE, "id": 9,
             "value": TLV.encode_value(55, "Integer")},
        ]}])
    rows = TLV.tlv_to_path_values("/3", body)
    by_path = {r["path"]: r for r in rows}
    assert by_path["/3/0/0"]["value"] == "ACME"
    assert by_path["/3/0/9"]["value"] == 55
    assert "Manufacturer" in by_path["/3/0/0"]["name"]
    # and the Write direction: rows → TLV → rows
    out = TLV.path_values_to_tlv("/3/0", [{"path": "9", "value": 70}])
    assert TLV.tlv_to_path_values("/3/0", out)[0]["value"] == 70


def test_lwm2m_tlv_read_response_and_typed_write():
    """End-to-end: a device's TLV Read response surfaces as typed rows
    in the up/response; a write command with content rows reaches the
    device as a TLV body with the TLV content-format."""
    async def main():
        from emqx_tpu.gateway import lwm2m_tlv as TLV
        app = BrokerApp()
        gw = app.gateway.load(Lwm2mGateway(port=0))
        await gw.start_listeners()
        uplinks = []
        app.hooks.add("message.publish",
                      lambda m: uplinks.append((m.topic, m.payload)) or None,
                      priority=-500)
        cli = CoapClient(gw.port)
        await cli.start()
        cli.request(C.POST, "rd", payload=b"</3/0>,</1/0>",
                    queries=["ep=tlv-ep"])
        await cli.recv()
        from emqx_tpu.core.message import Message

        # downlink read; device answers with a TLV body
        app.cm.dispatch(app.broker.publish(Message(
            topic="lwm2m/tlv-ep/dn/cmd",
            payload=json.dumps({"reqID": 9, "msgType": "read",
                                "data": {"path": "/3/0"}}).encode())))
        cmd = await cli.recv()
        body = TLV.tlv_encode([
            {"kind": TLV.RESOURCE, "id": 0, "value": b"ACME"},
            {"kind": TLV.RESOURCE, "id": 9,
             "value": TLV.encode_value(81, "Integer")}])
        cli.tr.sendto(cli.f.serialize(CoapMessage(
            C.ACK, C.CONTENT, cmd.mid, cmd.token,
            [(C.OPT_CONTENT_FORMAT,
              TLV.CONTENT_TLV.to_bytes(2, "big"))], body)))
        await asyncio.sleep(0.2)
        resp = json.loads(dict(uplinks)["lwm2m/tlv-ep/up/response"])
        rows = {r["path"]: r["value"] for r in resp["data"]["content"]}
        assert rows == {"/3/0/0": "ACME", "/3/0/9": 81}

        # typed write: content rows → TLV payload at the device
        app.cm.dispatch(app.broker.publish(Message(
            topic="lwm2m/tlv-ep/dn/cmd",
            payload=json.dumps({
                "reqID": 10, "msgType": "write",
                "data": {"basePath": "/1/0",
                         "content": [{"path": "1", "value": 7200}]},
            }).encode())))
        wcmd = await cli.recv()
        cf = wcmd.opt(C.OPT_CONTENT_FORMAT)
        assert int.from_bytes(cf, "big") == TLV.CONTENT_TLV
        decoded = TLV.tlv_to_path_values("/1/0", wcmd.payload)
        assert decoded[0]["value"] == 7200       # Lifetime, Integer-typed
        await gw.stop_listeners()
    run(main())


def test_lwm2m_tlv_write_nesting_and_malformed_rows():
    from emqx_tpu.gateway import lwm2m_tlv as TLV
    import pytest as _p
    # res-instance row nests MULTI_RES/RES_INSTANCE — NOT a flat
    # resource 0 (which would overwrite Manufacturer)
    body = TLV.path_values_to_tlv("/3/0", [
        {"path": "/3/0/6/0", "value": 1},
        {"path": "/3/0/6/1", "value": 5}])
    (entry,) = TLV.tlv_decode(body)
    assert entry["kind"] == TLV.MULTI_RES and entry["id"] == 6
    assert [c["id"] for c in entry["children"]] == [0, 1]
    # object base groups per-instance
    body = TLV.path_values_to_tlv("/3", [
        {"path": "/3/0/9", "value": 10}, {"path": "/3/1/9", "value": 20}])
    entries = TLV.tlv_decode(body)
    assert [(e["kind"], e["id"]) for e in entries] == \
        [(TLV.OBJ_INSTANCE, 0), (TLV.OBJ_INSTANCE, 1)]
    # malformed rows raise TlvError, never KeyError/IndexError
    for bad in ([{}], [{"path": "", "value": 1}],
                [{"path": "/9/0/1", "value": 1}],
                [{"path": "/3/a", "value": 1}]):
        with _p.raises(TLV.TlvError):
            TLV.path_values_to_tlv("/3/0", bad)


def test_lwm2m_malformed_write_falls_back_not_crash():
    """A write command with broken content rows must still reach the
    device (raw JSON), never crash CM.dispatch."""
    async def main():
        app = BrokerApp()
        gw = app.gateway.load(Lwm2mGateway(port=0))
        await gw.start_listeners()
        cli = CoapClient(gw.port)
        await cli.start()
        cli.request(C.POST, "rd", payload=b"</3/0>", queries=["ep=bad-ep"])
        await cli.recv()
        from emqx_tpu.core.message import Message
        app.cm.dispatch(app.broker.publish(Message(
            topic="lwm2m/bad-ep/dn/cmd",
            payload=json.dumps({"reqID": 1, "msgType": "write",
                                "data": {"basePath": "/3/0",
                                         "content": [{}]}}).encode())))
        cmd = await cli.recv()                  # delivered as raw JSON
        assert cmd.opt(C.OPT_CONTENT_FORMAT) is None
        assert b"msgType" in cmd.payload
        await gw.stop_listeners()
    run(main())


def test_lwm2m_tlv_notify_types_via_observed_path():
    """A TLV notify without ?path= types through the single
    outstanding observe; with no context it surfaces as hex."""
    async def main():
        from emqx_tpu.gateway import lwm2m_tlv as TLV
        app = BrokerApp()
        gw = app.gateway.load(Lwm2mGateway(port=0))
        await gw.start_listeners()
        uplinks = []
        app.hooks.add("message.publish",
                      lambda m: uplinks.append((m.topic, m.payload)) or None,
                      priority=-500)
        cli = CoapClient(gw.port)
        await cli.start()
        cli.request(C.POST, "rd", payload=b"</3/0>", queries=["ep=n-ep"])
        ack = await cli.recv()
        reg_id = ack.opts(C.OPT_LOCATION_PATH)[1].decode()
        from emqx_tpu.core.message import Message
        app.cm.dispatch(app.broker.publish(Message(
            topic="lwm2m/n-ep/dn/cmd",
            payload=json.dumps({"reqID": 3, "msgType": "observe",
                                "data": {"path": "/3/0"}}).encode())))
        await cli.recv()                        # the observe POST
        body = TLV.tlv_encode([
            {"kind": TLV.RESOURCE, "id": 9,
             "value": TLV.encode_value(64, "Integer")}])
        cli.request(C.POST, f"rd/{reg_id}/notify", payload=body,
                    options=[(C.OPT_CONTENT_FORMAT,
                              TLV.CONTENT_TLV.to_bytes(2, "big"))])
        await cli.recv()
        await asyncio.sleep(0.1)
        note = json.loads(dict(uplinks)["lwm2m/n-ep/up/notify"])
        assert note["payload"][0]["value"] == 64
        assert note["payload"][0]["path"] == "/3/0/9"
        await gw.stop_listeners()
    run(main())


# -- stomp transactions (emqx_stomp_channel BEGIN/COMMIT/ABORT) ----------------

def test_stomp_transactions_commit_abort_and_errors():
    async def main():
        app = BrokerApp()
        gw = app.gateway.load(ST.StompGateway(port=0))
        await gw.start_listeners()
        from emqx_tpu.broker.server import BrokerServer
        srv = BrokerServer(port=0, app=app)
        await srv.start()
        mq = MqttClient(port=srv.port, clientid="m1")
        await mq.connect()
        await mq.subscribe("tx/#")

        c = StompClient(gw.port)
        await c.connect()
        await c.send("CONNECT", {"accept-version": "1.2"})
        assert (await c.recv()).command == "CONNECTED"

        # deferred SENDs publish only on COMMIT, in order
        await c.send("BEGIN", {"transaction": "tx1"})
        await c.send("SEND", {"destination": "tx/a",
                              "transaction": "tx1"}, b"first")
        await c.send("SEND", {"destination": "tx/b",
                              "transaction": "tx1"}, b"second")
        await asyncio.sleep(0.2)
        assert mq.messages.empty(), "tx SEND leaked before COMMIT"
        await c.send("COMMIT", {"transaction": "tx1", "receipt": "r1"})
        rec = await c.recv()
        assert rec.command == "RECEIPT"
        m1, m2 = await mq.recv(), await mq.recv()
        assert (m1.topic, m1.payload) == ("tx/a", b"first")
        assert (m2.topic, m2.payload) == ("tx/b", b"second")

        # ABORT discards
        await c.send("BEGIN", {"transaction": "tx2"})
        await c.send("SEND", {"destination": "tx/c",
                              "transaction": "tx2"}, b"dropped")
        await c.send("ABORT", {"transaction": "tx2", "receipt": "r2"})
        assert (await c.recv()).command == "RECEIPT"
        await asyncio.sleep(0.2)
        assert mq.messages.empty()

        # unknown transaction on SEND → ERROR
        await c.send("SEND", {"destination": "tx/x",
                              "transaction": "nope"}, b"x")
        assert (await c.recv()).command == "ERROR"
        await mq.close()
        await gw.stop_listeners()
        await srv.stop()
    run(main())


def test_stomp_transaction_double_begin_and_timeout():
    from emqx_tpu.gateway.ctx import GwContext
    app = BrokerApp()
    ch = ST.Channel(GwContext(app, "stomp"))
    ch.conn_state = "connected"
    ch.clientid = "c1"
    assert ch.handle_in(ST.StompFrame("BEGIN", {"transaction": "t"})) == []
    out = ch.handle_in(ST.StompFrame("BEGIN", {"transaction": "t"}))
    assert out and out[0].command == "ERROR"
    # restart the channel state for timeout path
    ch2 = ST.Channel(GwContext(app, "stomp"))
    ch2.conn_state = "connected"
    ch2.clientid = "c2"
    ch2.tx_timeout_s = 0.0
    ch2.handle_in(ST.StompFrame("BEGIN", {"transaction": "t2"}))
    ch2.housekeep()                       # expires immediately
    out = ch2.handle_in(ST.StompFrame(
        "COMMIT", {"transaction": "t2"}))
    assert out and out[0].command == "ERROR"


def test_stomp_kicked_client_cannot_publish_and_socket_drops():
    """An admin kick closes the transport and the channel drops any
    frame that still arrives — no post-kick publish."""
    async def main():
        app = BrokerApp()
        gw = app.gateway.load(ST.StompGateway(port=0))
        await gw.start_listeners()
        from emqx_tpu.broker.server import BrokerServer
        srv = BrokerServer(port=0, app=app)
        await srv.start()
        mq = MqttClient(port=srv.port, clientid="watch")
        await mq.connect()
        await mq.subscribe("#")
        c = StompClient(gw.port)
        await c.connect()
        await c.send("CONNECT", {"accept-version": "1.2",
                                 "client-id": "victim"})
        await c.recv()
        assert app.cm.kick("victim")
        # the transport drops; a racing SEND must not publish
        try:
            await c.send("SEND", {"destination": "post/kick"}, b"leak")
        except ConnectionError:
            pass
        await asyncio.sleep(0.3)
        assert mq.messages.empty(), "kicked client published"
        try:
            data = await asyncio.wait_for(c.r.read(64), 5)
            assert data == b"", "socket not closed by kick"
        except ConnectionResetError:
            pass                      # RST is also a closed transport
        await mq.close()
        await gw.stop_listeners()
        await srv.stop()
    run(main())


def test_stomp_tx_swept_by_tcp_listener_tick():
    """The TCP listener's housekeeping tick expires abandoned
    transactions (they are not dead code on the TCP transport)."""
    async def main():
        app = BrokerApp()
        gw = app.gateway.load(ST.StompGateway(port=0))
        await gw.start_listeners()
        gw.listener.tick_interval_s = 0.05
        c = StompClient(gw.port)
        await c.connect()
        await c.send("CONNECT", {"accept-version": "1.2"})
        await c.recv()
        await c.send("BEGIN", {"transaction": "stale"})
        await asyncio.sleep(0.1)
        (conn,) = gw.listener.connections
        conn.channel.tx_timeout_s = 0.0
        await asyncio.sleep(0.3)            # tick sweeps it
        assert conn.channel._tx == {}
        await gw.stop_listeners()
    run(main())


def test_lwm2m_tlv_write_duplicate_and_mixed_rows_rejected():
    from emqx_tpu.gateway import lwm2m_tlv as TLV
    import pytest as _p
    with _p.raises(TLV.TlvError):
        TLV.path_values_to_tlv("/3/0", [{"path": "13", "value": 1},
                                        {"path": "13", "value": 2}])
    with _p.raises(TLV.TlvError):
        TLV.path_values_to_tlv("/3/0", [{"path": "/3/0/6/0", "value": 1},
                                        {"path": "/3/0/6", "value": 9}])


def test_stomp_disconnect_clears_gateway_session():
    """Graceful DISCONNECT (and ERROR teardown) must drop the session
    from ctx.sessions — no ghost clients in the REST surface."""
    async def main():
        app = BrokerApp()
        gw = app.gateway.load(ST.StompGateway(port=0))
        await gw.start_listeners()
        ctx = app.gateway.contexts["stomp"]
        c = StompClient(gw.port)
        await c.connect()
        await c.send("CONNECT", {"accept-version": "1.2",
                                 "client-id": "ghost?"})
        await c.recv()
        assert "ghost?" in ctx.sessions
        await c.send("DISCONNECT", {"receipt": "bye"})
        await c.recv()
        await asyncio.sleep(0.3)
        assert "ghost?" not in ctx.sessions
        assert app.cm.lookup_channel("ghost?") is None
        await gw.stop_listeners()
    run(main())


def test_stomp_error_never_carries_receipt():
    """A failed frame with a receipt header answers ERROR only — a
    RECEIPT would claim an expired/bogus COMMIT succeeded."""
    from emqx_tpu.gateway.ctx import GwContext
    app = BrokerApp()
    ch = ST.Channel(GwContext(app, "stomp"))
    ch.conn_state = "connected"
    ch.clientid = "c1"
    out = ch.handle_in(ST.StompFrame(
        "COMMIT", {"transaction": "nope", "receipt": "r9"}))
    assert [f.command for f in out] == ["ERROR"]


def test_sn_reconnect_with_new_clientid_releases_old_session():
    async def main():
        app = BrokerApp()
        gw = app.gateway.load(SN.MqttsnGateway(port=0))
        await gw.start_listeners()
        ctx = app.gateway.contexts["mqttsn"]
        dev = SnClient(gw.port)
        await dev.start()
        dev.send(SN.SnMessage(SN.CONNECT, clientid="old-id"))
        await dev.recv()
        assert "old-id" in ctx.sessions
        dev.send(SN.SnMessage(SN.CONNECT, clientid="new-id"))
        await dev.recv()
        assert "old-id" not in ctx.sessions      # no ghost
        assert "new-id" in ctx.sessions
        await gw.stop_listeners()
    run(main())


def test_stomp_verb_connect_alias_no_receipt():
    from emqx_tpu.gateway.ctx import GwContext
    app = BrokerApp()
    ch = ST.Channel(GwContext(app, "stomp"))
    out = ch.handle_in(ST.StompFrame(
        "STOMP", {"accept-version": "1.2", "receipt": "r0"}))
    assert [f.command for f in out] == ["CONNECTED"]


def test_sn_rejected_reconnect_deauthenticates():
    """A re-CONNECT as a banned clientid must drop the channel back to
    idle — no publishing as the denied identity."""
    async def main():
        app = BrokerApp()
        app.access.banned.create("clientid", "banned-dev")
        gw = app.gateway.load(SN.MqttsnGateway(port=0))
        await gw.start_listeners()
        ctx = app.gateway.contexts["mqttsn"]
        dev = SnClient(gw.port)
        await dev.start()
        dev.send(SN.SnMessage(SN.CONNECT, clientid="good-dev"))
        assert (await dev.recv()).rc == SN.RC_ACCEPTED
        dev.send(SN.SnMessage(SN.CONNECT, clientid="banned-dev"))
        assert (await dev.recv()).rc != SN.RC_ACCEPTED
        (ch,) = gw.listener.channels.values()
        assert ch.conn_state != "connected" and ch.clientid is None
        assert "good-dev" not in ctx.sessions        # old one released
        await gw.stop_listeners()
    run(main())


def test_sn_same_clientid_denied_reconnect_releases_session():
    """Freshly-banned device re-CONNECTs under the SAME clientid: the
    denial must release the old session, not leak it as a ghost."""
    async def main():
        app = BrokerApp()
        gw = app.gateway.load(SN.MqttsnGateway(port=0))
        await gw.start_listeners()
        ctx = app.gateway.contexts["mqttsn"]
        dev = SnClient(gw.port)
        await dev.start()
        dev.send(SN.SnMessage(SN.CONNECT, clientid="dev-x"))
        assert (await dev.recv()).rc == SN.RC_ACCEPTED
        app.access.banned.create("clientid", "dev-x")
        dev.send(SN.SnMessage(SN.CONNECT, clientid="dev-x"))
        assert (await dev.recv()).rc != SN.RC_ACCEPTED
        assert "dev-x" not in ctx.sessions
        assert app.cm.lookup_channel("dev-x") is None
        await gw.stop_listeners()
    run(main())


def test_stomp_error_frame_closes_connection():
    """STOMP 1.2: after sending an ERROR frame the server MUST close
    the connection — the client receives the ERROR, then EOF; no
    half-open session that silently swallows subsequent frames
    (round-3 advisor finding, gateway/stomp.py _error)."""
    async def main():
        app = BrokerApp()
        gw = app.gateway.load(ST.StompGateway(port=0))
        await gw.start_listeners()
        c = StompClient(gw.port)
        await c.connect()
        await c.send("CONNECT", {"accept-version": "1.2",
                                 "client-id": "errc"})
        assert (await c.recv()).command == "CONNECTED"
        await c.send("SEND", {}, b"no destination header")
        err = await c.recv()
        assert err.command == "ERROR"
        # server closes right after the ERROR frame
        data = await asyncio.wait_for(c.r.read(64), 5)
        assert data == b"", "socket left open after ERROR"
        # session is torn down, not leaked
        await asyncio.sleep(0.1)
        assert app.cm.lookup_channel("errc") is None
        await gw.stop_listeners()
    run(main())
