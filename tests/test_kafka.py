"""Kafka producer stack: wire codecs (crc32c, zigzag varints, record
batch v2, murmur2 partitioning), client vs the in-repo MiniKafka broker,
and the rule→bridge→Kafka produce path (emqx_ee_bridge_kafka/wolff
ground truth; the reference's CI drives a real Kafka container)."""

import time

import pytest

from emqx_tpu.app import BrokerApp
from emqx_tpu.connector.kafka import (KafkaClient, KafkaConnector,
                                      KafkaError, MiniKafka, crc32c,
                                      decode_record_batch,
                                      encode_record_batch, murmur2,
                                      read_varint, varint)
from emqx_tpu.core.message import Message


def test_crc32c_vectors():
    # RFC 3720 B.4 / golang hash/crc32 Castagnoli vectors
    assert crc32c(b"") == 0
    assert crc32c(b"123456789") == 0xE3069283
    assert crc32c(b"\x00" * 32) == 0x8A9136AA


def test_varint_zigzag_roundtrip():
    for n in (0, 1, -1, 63, -64, 300, -300, 2**20, -(2**20), 2**42):
        v, pos = read_varint(varint(n), 0)
        assert (v, pos) == (n, len(varint(n)))


def test_record_batch_roundtrip_and_crc_enforced():
    batch = encode_record_batch(
        [(b"k", b"v1"), (None, b"v2"), (b"k3", b"")], base_ts=1234)
    assert decode_record_batch(batch) == [
        (b"k", b"v1"), (None, b"v2"), (b"k3", b"")]
    corrupted = bytearray(batch)
    corrupted[-1] ^= 0xFF
    with pytest.raises(KafkaError, match="CRC"):
        decode_record_batch(bytes(corrupted))


def test_produce_partitioning_and_offsets():
    srv = MiniKafka(topics={"t3": 3}).start()
    try:
        c = KafkaClient(port=srv.port)
        assert c.partitions("t3") == 3
        offs = [c.produce("t3", f"m{i}".encode(), key=b"same-key")
                for i in range(3)]
        assert offs == [0, 1, 2]            # same key → one partition
        # the stored records survived CRC validation server-side
        (part,) = {p for (t, p) in srv.records if t == "t3"}
        assert [v for _k, v in srv.records[("t3", part)]] == \
            [b"m0", b"m1", b"m2"]
        # keyless spreads round-robin
        for i in range(6):
            c.produce("t3", b"rr")
        assert len({p for (t, p) in srv.records if t == "t3"}) == 3
        c.close()
    finally:
        srv.stop()


def test_connector_health_and_reconnect():
    srv = MiniKafka().start()
    conn = KafkaConnector(port=srv.port)
    try:
        conn.on_start({})
        assert conn.on_health_check()
        off = conn.on_query({"topic": "events", "key": "k", "value": "v"})
        assert off == 0
        conn.client.close()                # stale pooled conn
        assert conn.on_query(
            {"topic": "events", "key": "k", "value": "v2"}) == 1
        conn.on_stop()
    finally:
        srv.stop()


def test_rule_to_kafka_bridge():
    """message.publish → rule → kafka bridge → record lands in the
    broker with the templated key/value."""
    srv = MiniKafka(topics={"mqtt-up": 2}).start()
    try:
        app = BrokerApp()
        app.bridges.create(
            "kafka", "up", KafkaConnector(port=srv.port),
            {"kafka_topic": "mqtt-up",
             "key_template": "${clientid}",
             "value_template": '{"t":"${topic}","p":"${payload}"}'},
            batch_size=1, batch_time_s=0.0)
        app.rules.create_rule(
            "to-kafka", 'SELECT clientid, topic, payload FROM "k/#"',
            [{"function": "kafka:up", "args": {}}])
        app.broker.publish(Message(topic="k/1", payload=b"hello",
                                   from_="dev-a"))
        deadline = 50
        while not srv.records and deadline:
            time.sleep(0.1)
            app.bridges.tick()
            deadline -= 1
        ((topic, _pid),) = srv.records.keys()
        assert topic == "mqtt-up"
        ((key, value),) = list(srv.records.values())[0]
        assert key == b"dev-a"
        assert value == b'{"t":"k/1","p":"hello"}'
        assert murmur2(b"dev-a") & 0x7FFFFFFF  # partitioner exercised
    finally:
        srv.stop()


def test_leader_routing_across_brokers():
    """Metadata names another broker as partition leader: the client must
    connect THERE; a produce answered NOT_LEADER refreshes and retries."""
    leader = MiniKafka(topics={"lt": 1}, node_id=1).start()
    boot = MiniKafka(topics={"lt": 1}, node_id=0,
                     redirect_to=leader).start()
    try:
        c = KafkaClient(port=boot.port)      # bootstrap = non-leader
        off = c.produce("lt", b"routed", key=b"k")
        assert off == 0
        assert boot.records == {}            # nothing stored on non-leader
        assert [v for _k, v in leader.records[("lt", 0)]] == [b"routed"]
        c.close()
    finally:
        boot.stop()
        leader.stop()


def test_batch_produce_one_request_per_partition():
    srv = MiniKafka(topics={"bt": 2}).start()
    try:
        conn = KafkaConnector(port=srv.port)
        reqs = [{"topic": "bt", "key": f"k{i % 2}", "value": f"v{i}"}
                for i in range(10)]
        offs = conn.on_batch_query(reqs)
        total = sum(len(v) for v in srv.records.values())
        assert total == 10
        # offsets are per-partition sequential; within one key (= one
        # partition) they strictly increase
        for kmod in (0, 1):
            per_key = [offs[i] for i in range(10) if i % 2 == kmod]
            assert per_key == sorted(per_key)
            assert len(set(per_key)) == len(per_key)
        # non-string values coerce to JSON instead of crashing
        assert conn.on_query({"topic": "bt", "key": "k",
                              "value": {"a": 1}}) >= 0
        stored = [v for recs in srv.records.values() for _k, v in recs]
        assert b'{"a": 1}' in stored
        conn.on_stop()
    finally:
        srv.stop()
