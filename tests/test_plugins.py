"""Plugins, dashboard monitor, swagger generation — emqx_plugins_SUITE /
emqx_dashboard_monitor_SUITE mirrors."""

import json

from emqx_tpu.app import BrokerApp
from emqx_tpu.core.message import Message
from emqx_tpu.mgmt import swagger
from emqx_tpu.mgmt.api import ManagementApi
from emqx_tpu.observe.monitor import DashboardMonitor
from emqx_tpu.services.plugins import PluginManager

PLUGIN_PY = '''
STARTED = []

def on_start(app):
    app.hooks.add("message.publish", _tag, priority=900)
    STARTED.append(True)

def on_stop(app):
    app.hooks.delete("message.publish", _tag)

def _tag(msg):
    return msg.set_header("via_plugin", True)
'''


def _mk_plugin(root, name_vsn="tagger-1.0.0", desc="tags messages"):
    pdir = root / name_vsn
    pdir.mkdir(parents=True)
    (pdir / "release.json").write_text(json.dumps(
        {"name": name_vsn.split("-")[0], "rel_vsn": "1.0.0",
         "description": desc}))
    (pdir / "plugin.py").write_text(PLUGIN_PY)
    return pdir


def test_plugin_lifecycle_and_hook_effect(tmp_path):
    _mk_plugin(tmp_path)
    app = BrokerApp()
    pm = PluginManager(app, str(tmp_path))
    assert pm.scan() == ["tagger-1.0.0"]
    pm.ensure_enabled("tagger-1.0.0")
    pm.ensure_started()
    assert pm.describe("tagger-1.0.0")["running"]
    # the plugin's hook actually runs in the publish pipeline
    seen = []
    app.hooks.add("message.publish",
                  lambda m: seen.append(m.headers.get("via_plugin")) or None,
                  priority=800)
    app.broker.publish(Message(topic="p/t", payload=b"x"))
    assert seen == [True]
    pm.ensure_stopped("tagger-1.0.0")
    seen.clear()
    app.broker.publish(Message(topic="p/t", payload=b"x"))
    assert seen == [None]                     # hook detached on stop
    assert pm.ensure_uninstalled("tagger-1.0.0")
    assert pm.list() == []


def test_plugin_error_isolated(tmp_path):
    pdir = tmp_path / "broken-0.1.0"
    pdir.mkdir()
    (pdir / "release.json").write_text('{"name": "broken"}')
    (pdir / "plugin.py").write_text("def on_start(app):\n    boom()\n")
    app = BrokerApp()
    pm = PluginManager(app, str(tmp_path))
    pm.scan()
    pm.ensure_enabled("broken-0.1.0")
    pm.ensure_started()                       # must not raise
    d = pm.describe("broken-0.1.0")
    assert not d["running"] and "NameError" in d["error"]


def test_dashboard_monitor_rates_and_history():
    app = BrokerApp()
    mon = DashboardMonitor(app, interval_s=10)
    mon.sample(now=1000.0)
    app.metrics.inc("messages.received", 50)
    app.metrics.inc("messages.sent", 30)
    point = mon.sample(now=1010.0)
    assert point["received_rate"] == 5.0 and point["sent_rate"] == 3.0
    assert not mon.tick(now=1011.0)           # inside interval
    assert mon.tick(now=1021.0)
    assert len(mon.history()) == 3
    cur = mon.current()
    assert cur["messages.received"] == 50 and "received_rate" in cur


def test_swagger_from_routes_and_schema():
    app = BrokerApp()
    api = ManagementApi(app)
    doc = swagger.generate(api)
    assert doc["openapi"].startswith("3.")
    assert "/api/v5/clients/{clientid}" in doc["paths"]
    ops = doc["paths"]["/api/v5/clients/{clientid}"]
    assert "get" in ops and "delete" in ops
    assert ops["get"]["parameters"][0]["name"] == "clientid"
    cfg = doc["components"]["schemas"]["Config"]
    assert cfg["properties"]["mqtt"]["properties"][
        "max_packet_size"]["type"] == "string"
    assert cfg["properties"]["retainer"]["additionalProperties"] is True


def test_plugin_state_persists_and_uninstall_purges(tmp_path):
    _mk_plugin(tmp_path)
    app = BrokerApp()
    pm = PluginManager(app, str(tmp_path))
    pm.scan()
    pm.ensure_enabled("tagger-1.0.0")
    # a fresh manager (broker restart) sees the persisted enablement
    pm2 = PluginManager(BrokerApp(), str(tmp_path))
    pm2.scan()
    assert pm2.plugins["tagger-1.0.0"].enabled
    pm2.ensure_started()
    assert pm2.describe("tagger-1.0.0")["running"]
    # uninstall purges the package dir — a rescan cannot resurrect it
    assert pm2.ensure_uninstalled("tagger-1.0.0")
    assert pm2.scan() == [] and pm2.list() == []


def test_auto_subscribe_respects_acl():
    from emqx_tpu.broker.channel import Channel
    from emqx_tpu.broker.hooks import Hooks
    from emqx_tpu.mqtt import packet as P

    app = BrokerApp()
    app.auto_subscribe.add("ok/%c")
    app.auto_subscribe.add("secret/%c")
    app.hooks.add(
        "client.authorize",
        lambda ci, action, topic, acc:
            (Hooks.STOP, "deny") if topic.startswith("secret/") else None,
        priority=2000)
    ch = Channel(app.broker, app.cm)
    ch.handle_in(P.Connect(proto_ver=P.MQTT_V5, clientid="acl-1"))
    assert ("acl-1", "ok/acl-1") in app.broker.suboption
    assert ("acl-1", "secret/acl-1") not in app.broker.suboption
