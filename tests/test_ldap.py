"""LDAP stack: BER codec + RFC 4515 filters, the LDAPv3 wire client
against MiniLDAP, and authn/authz through a live broker (reference:
emqx_connector_ldap.erl search/4; CI runs a real openldap container)."""

import asyncio

import pytest

from emqx_tpu.app import BrokerApp
from emqx_tpu.broker.server import BrokerServer
from emqx_tpu.config.config import Config
from emqx_tpu.connector.ldap import (LdapClient, LdapConnector, LdapError,
                                     MiniLDAP, ber_read, ber_seq,
                                     parse_filter)
from emqx_tpu.mqtt.client import MqttClient


def _directory() -> MiniLDAP:
    srv = MiniLDAP()
    srv.add("uid=alice,ou=mqtt,dc=emqx,dc=io",
            objectClass=["mqttUser"], uid="alice",
            userPassword="pw-alice", isSuperuser="false",
            mqttPublishTopic="up/alice/#", mqttSubscriptionTopic="up/#")
    srv.add("uid=bob,ou=mqtt,dc=emqx,dc=io",
            objectClass=["mqttUser"], uid="bob",
            userPassword="pw-bob", isSuperuser="true")
    srv.add("ou=mqtt,dc=emqx,dc=io", objectClass=["organizationalUnit"],
            ou="mqtt")
    return srv


# -- BER / filter unit tests ---------------------------------------------------

def test_ber_long_length_roundtrip():
    from emqx_tpu.connector.ldap import ber
    content = b"x" * 300
    tag, got, used = ber_read(ber(0x30, content), 0)
    assert (tag, got) == (0x30, content) and used == 300 + 4


def test_filter_parse_shapes():
    # equality, presence, and, or, not, substring all encode
    for s in ("(uid=alice)", "(uid=*)", "(&(a=1)(b=2))",
              "(|(a=1)(!(b=2)))", "(cn=al*ce*)", "(n>=5)"):
        tlv = parse_filter(s)
        ber_read(tlv, 0)   # well-formed
    with pytest.raises(LdapError):
        parse_filter("(uid=alice")
    with pytest.raises(LdapError):
        parse_filter("(&)")
    with pytest.raises(LdapError):
        parse_filter("(nooper)")


def test_filter_escapes():
    tlv = parse_filter(r"(cn=a\2ab)")        # \2a = literal '*'
    _tag, content, _ = ber_read(tlv, 0)
    parts = ber_seq(content)
    assert parts[1][1] == b"a*b"
    for bad in (r"(cn=a\zz)", "(cn=a\\5)"):  # non-hex / truncated escape
        with pytest.raises(LdapError):
            parse_filter(bad)


def test_filter_injection_blocked():
    """${username} substitution must RFC 4515-escape metacharacters: a
    username of 'al*' must not wildcard-match alice's entry."""
    srv = _directory().start()
    try:
        from emqx_tpu.access.ldap_backends import LdapAuthnProvider
        p = LdapAuthnProvider(LdapClient(port=srv.port))
        assert p.authenticate(
            {"username": "al*", "password": b"pw-alice"}) == "ignore"
        # and the escaped literal still matches an exact entry
        assert p.authenticate(
            {"username": "alice", "password": b"pw-alice"})[0] == "ok"
    finally:
        srv.stop()


def test_empty_password_is_not_unauthenticated_bind():
    """RFC 4513 §5.1.2: empty password must fail authn outright, never
    reach the server as an unauthenticated bind."""
    srv = _directory().start()
    try:
        from emqx_tpu.access.ldap_backends import LdapAuthnProvider
        p = LdapAuthnProvider(LdapClient(port=srv.port))
        assert p.authenticate(
            {"username": "alice", "password": b""}) == (
                "error", "bad_username_or_password")
    finally:
        srv.stop()


def test_scope_respects_dn_component_boundary():
    """A sibling tree whose string merely ends with the base DN is out
    of scope (comma-boundary check)."""
    srv = MiniLDAP()
    srv.add("cn=x,otherdc=emqx,dc=io", cn="x")
    srv.add("cn=y,dc=emqx,dc=io", cn="y")
    srv.start()
    try:
        c = LdapClient(port=srv.port)
        hits = c.search("dc=emqx,dc=io", "(cn=*)")
        assert [dn for dn, _ in hits] == ["cn=y,dc=emqx,dc=io"]
        c.close()
    finally:
        srv.stop()


# -- wire client vs MiniLDAP ---------------------------------------------------

def test_ldap_search_and_bind():
    srv = _directory().start()
    try:
        c = LdapClient(port=srv.port, bind_dn="cn=admin,dc=emqx,dc=io",
                       bind_password="admin")
        hits = c.search("dc=emqx,dc=io",
                        "(&(objectClass=mqttUser)(uid=alice))")
        assert len(hits) == 1
        dn, attrs = hits[0]
        assert dn == "uid=alice,ou=mqtt,dc=emqx,dc=io"
        assert attrs["mqttpublishtopic"] == ["up/alice/#"]
        # attribute selection narrows the entry
        hits = c.search("dc=emqx,dc=io", "(uid=alice)", ("uid",))
        assert list(hits[0][1]) == ["uid"]
        # presence + substring + scope=one
        assert len(c.search("dc=emqx,dc=io", "(uid=*)")) == 2
        assert len(c.search("dc=emqx,dc=io", "(uid=*li*)")) == 1
        one = c.search("dc=emqx,dc=io", "(objectClass=*)", scope="one")
        assert [dn for dn, _ in one] == ["ou=mqtt,dc=emqx,dc=io"]
        assert len(c.search("ou=mqtt,dc=emqx,dc=io", "(objectClass=*)",
                            scope="one")) == 2
        # bind-as-user password check
        assert c.check_bind("uid=alice,ou=mqtt,dc=emqx,dc=io", "pw-alice")
        assert not c.check_bind("uid=alice,ou=mqtt,dc=emqx,dc=io", "nope")
        c.close()
        # wrong root bind refused at connect time
        bad = LdapClient(port=srv.port, bind_dn="cn=admin,dc=emqx,dc=io",
                         bind_password="wrong")
        with pytest.raises(LdapError):
            bad.search("dc=emqx,dc=io", "(uid=*)")
    finally:
        srv.stop()


def test_ldap_connector_resource():
    srv = _directory().start()
    try:
        conn = LdapConnector(port=srv.port)
        conn.on_start({})
        assert conn.on_health_check()
        hits = conn.on_query({"search": "dc=emqx,dc=io",
                              "filter": "(uid=bob)",
                              "attributes": ("isSuperuser",)})
        assert hits[0][1]["issuperuser"] == ["true"]
        assert conn.on_query({"bind": "uid=bob,ou=mqtt,dc=emqx,dc=io",
                              "password": "pw-bob"})
        conn.on_stop()
        assert conn.on_health_check()   # lazily reconnects
    finally:
        srv.stop()


def test_ldap_client_survives_server_restart():
    srv = _directory().start()
    port = srv.port
    c = LdapClient(port=port)
    assert len(c.search("dc=emqx,dc=io", "(uid=*)")) == 2
    srv.stop()
    srv2 = MiniLDAP(port=port)
    srv2.add("uid=carol,dc=emqx,dc=io", uid="carol")
    srv2.start()
    try:
        # retry-once reconnect picks the fresh server up
        assert len(c.search("dc=emqx,dc=io", "(uid=*)")) == 1
        c.close()
    finally:
        srv2.stop()


# -- authn / authz through a live broker ---------------------------------------

def test_ldap_authn_authz_via_live_broker():
    srv = _directory().start()

    async def main():
        conf = Config()
        conf.init_load("authorization { no_match = deny }")
        spec = {"mechanism": "password_based", "backend": "ldap",
                "server": f"127.0.0.1:{srv.port}",
                "base_dn": "dc=emqx,dc=io"}
        conf.put("authentication", [spec], layer="local")
        conf.put("authorization.sources",
                 [{**spec, "type": "ldap"}], layer="local")
        app = BrokerApp.from_config(conf)
        server = BrokerServer(port=0, app=app)
        await server.start()

        bad = MqttClient(port=server.port, clientid="b1", proto_ver=5,
                         username="alice", password=b"wrong")
        with pytest.raises(ConnectionRefusedError):
            await bad.connect()

        good = MqttClient(port=server.port, clientid="g1", proto_ver=5,
                          username="alice", password=b"pw-alice")
        ack = await good.connect()
        assert ack.reason_code == 0

        sub = MqttClient(port=server.port, clientid="s1", proto_ver=5,
                         username="alice", password=b"pw-alice")
        await sub.connect()
        await sub.subscribe("up/#", qos=0)
        await good.publish("up/alice/data", b"ok", qos=0)
        await good.publish("other/topic", b"denied", qos=0)
        try:
            msg = await asyncio.wait_for(sub.messages.get(), 5)
            assert msg.topic == "up/alice/data"
            assert sub.messages.empty()
        finally:
            await good.disconnect()
            await sub.disconnect()
            await server.stop()

    try:
        asyncio.run(main())
    finally:
        srv.stop()


def test_scope_sub_includes_base_entry():
    """RFC 4511 wholeSubtree includes the base object itself; onelevel
    does not (round-3 advisor finding, connector/ldap.py _in_scope)."""
    srv = MiniLDAP()
    srv.add("ou=mqtt,dc=emqx,dc=io", objectClass=["organizationalUnit"],
            ou="mqtt")
    srv.add("uid=a,ou=mqtt,dc=emqx,dc=io", objectClass=["mqttUser"],
            uid="a")
    srv.start()
    try:
        c = LdapClient(port=srv.port)
        sub = c.search("ou=mqtt,dc=emqx,dc=io", "(objectClass=*)",
                       scope="sub")
        assert {dn for dn, _ in sub} == {"ou=mqtt,dc=emqx,dc=io",
                                         "uid=a,ou=mqtt,dc=emqx,dc=io"}
        one = c.search("ou=mqtt,dc=emqx,dc=io", "(objectClass=*)",
                       scope="one")
        assert [dn for dn, _ in one] == ["uid=a,ou=mqtt,dc=emqx,dc=io"]
        c.close()
    finally:
        srv.stop()


def test_ber_truncation_vs_malformation():
    """The client's recv loop retries only on truncation; structurally
    malformed BER (X.690 indefinite length, forbidden in LDAP) must
    fail fast instead of spinning until the socket timeout."""
    from emqx_tpu.connector.ldap import TruncatedBer, ber
    with pytest.raises(TruncatedBer):
        ber_read(b"\x30", 0)                       # header cut short
    with pytest.raises(TruncatedBer):
        ber_read(b"\x30\x82\x01", 0)               # length bytes cut
    with pytest.raises(TruncatedBer):
        ber_read(b"\x30\x05abc", 0)                # content cut short
    with pytest.raises(LdapError) as ei:
        ber_read(b"\x30\x80abc\x00\x00", 0)        # indefinite form
    assert not isinstance(ei.value, TruncatedBer)
    tag, content, used = ber_read(ber(0x30, b"ok"), 0)
    assert (tag, content) == (0x30, b"ok")
