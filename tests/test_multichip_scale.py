"""Multi-device proof at engagement scale (VERDICT r3 #6, widened in
round 7 per VERDICT weak #7): the same 100k-filter set must route
identically on a single device and every (dp, tp) split of an 8-device
mesh — tp ∈ {1, 2, 4}, including dense-pool (high-degree) filters under
tp-sharding and an UNEVEN final batch (B not divisible by the mesh
extent) — the FULL serving stack (broker + pipeline + kernel) must run
on a mesh end-to-end, and a device loss mid-serving must fail over to
the host oracle without dropping deliveries. Reference frame: SURVEY
§2.5-3/4; the mesh axes are emqx's subscriber sharding re-expressed as
jax.sharding (parallel/mesh.py)."""

import asyncio

import numpy as np
import pytest

from emqx_tpu.models.router_model import RouterModel
from emqx_tpu.parallel.mesh import make_mesh
from emqx_tpu.router.index import ShardedTrieIndex, TrieIndex

N_SLOTS = 64 * 32 * 2      # divisible by 32*tp for every tp in {1,2,4}

TRIE_SHARDS = 4            # divisible by every tp extent in MESH_SHAPES

# every 8-device (dp, tp) split: tp=1 (pure data parallel), the default
# 4x2, and tp=4 (fan-out-heavy) — tp-sharding must stay a pure layout
# choice at each point
MESH_SHAPES = [(8, 1), (4, 2), (2, 4)]


def _populate(model, n=110_000, dense_fids=8, dense_degree=100):
    """Connected-vehicle tree with >=100k distinct filters, crossing
    the vectorized-build threshold, plus a few high-degree filters that
    promote into the device dense pool (degree > dense_threshold=64)."""
    rng = np.random.default_rng(5)
    for i in range(n):
        kind = i % 4
        metric = int(rng.integers(0, 8))
        if kind == 0:
            f = f"vehicle/v{i}/telemetry/m{metric}"
        elif kind == 1:
            f = f"vehicle/+/telemetry/z{i}"
        elif kind == 2:
            f = f"vehicle/v{i}/#"
        else:
            f = f"fleet/f{i}/vehicle/+/status/#"
        model.subscribe(f, int(rng.integers(0, N_SLOTS)))
    for d in range(dense_fids):
        f = f"broadcast/alerts/region{d}/#"
        for s in range(dense_degree):
            model.subscribe(f, (d * dense_degree + s) % N_SLOTS)
    model.refresh()


def _topics(n=128):
    rng = np.random.default_rng(9)
    out = []
    for i in range(n):
        k = i % 4
        if k == 0:
            # hits a kind-2 "vehicle/v{j}/#" (j % 4 == 2) plus possibly
            # the kind-0 exact and kind-1 '+' filters
            j = int(rng.integers(0, 110_000 // 4)) * 4 + 2
            out.append(f"vehicle/v{j}/telemetry/m{int(rng.integers(0, 8))}")
        elif k == 1:
            j = int(rng.integers(0, 110_000 // 4)) * 4 + 3
            out.append(f"fleet/f{j}/vehicle/vX/status/ok")
        elif k == 2:
            out.append(f"broadcast/alerts/region{i % 8}/storm")
        else:
            out.append("no/subscribers/here")
    return out


def _build_model(mesh=None):
    model = RouterModel(TrieIndex(max_levels=8), n_sub_slots=N_SLOTS,
                        K=32, M=64, mesh=mesh)
    _populate(model)
    return model


@pytest.fixture(scope="module")
def single_model():
    import jax

    assert len(jax.devices()) >= 8
    model = _build_model()
    n_distinct = sum(f is not None for f in model.index.filters)
    assert n_distinct >= 100_000, n_distinct
    assert len(model._dense_row) >= 8, "dense pool not populated"
    return model


@pytest.fixture(scope="module")
def sharded_models():
    """One populated model per mesh shape, built lazily and cached for
    the whole parametrized matrix (a fresh 110k-filter build per case
    would dominate the suite)."""
    cache: dict = {}

    def get(shape):
        if shape not in cache:
            mesh = make_mesh(8, shape=shape)
            model = _build_model(mesh)
            assert len(model._dense_row) >= 8
            cache[shape] = model
        return cache[shape]

    return get


# 3 shapes x 2 batch geometries = 6 parity cases. nbatch=77 is the
# UNEVEN final batch: 77 is divisible by none of dp, tp, or dp*tp for
# any shape here, so the kernel's padding row must mask out cleanly.
@pytest.mark.parametrize("shape", MESH_SHAPES,
                         ids=[f"dp{d}tp{t}" for d, t in MESH_SHAPES])
@pytest.mark.parametrize("nbatch", [128, 77], ids=["aligned", "uneven"])
def test_parity_single_vs_mesh_at_100k(single_model, sharded_models,
                                       shape, nbatch):
    sharded = sharded_models(shape)
    topics = _topics()[:nbatch]
    r1 = single_model.publish_batch(topics)
    r2 = sharded.publish_batch(topics)
    # matched filters, aux matches, fan-out slots and fallback set must
    # be identical — (dp, tp) sharding (incl. the dense-pool OR) is a
    # pure layout choice, never a semantic one
    assert r1[0] == r2[0]
    assert r1[1] == r2[1]
    assert [sorted(s) for s in r1[2]] == [sorted(s) for s in r2[2]]
    assert r1[3] == r2[3]
    # the dense broadcast filters actually fanned out at high degree
    bcast_rows = [j for j, t in enumerate(topics)
                  if t.startswith("broadcast/")]
    assert bcast_rows
    for j in bcast_rows:
        assert len(r1[2][j]) >= 90, len(r1[2][j])


# -- subscription-sharded trie (ISSUE 17): the fid space partitioned
# over tp instead of replicating the whole trie per device ------------


@pytest.fixture(scope="module")
def sharded_trie_models():
    """One populated SHARDED-trie model per mesh shape (S=4 trie shards
    stacked over tp), cached across the parametrized matrix. The 110k
    host build (subscribe loop + per-shard trie rebuild + pool build)
    happens ONCE: later shapes share the same ShardedTrieIndex and
    clone the first model's host-side sub-state, paying only their own
    device upload + compile — a fresh build per shape would add ~30s
    of pure host-side repetition to tier-1."""
    cache: dict = {}

    def get(shape):
        if shape not in cache:
            mesh = make_mesh(8, shape=shape)
            if not cache:
                model = RouterModel(
                    ShardedTrieIndex(TRIE_SHARDS, max_levels=8),
                    n_sub_slots=N_SLOTS, K=32, M=64, mesh=mesh)
                _populate(model)
            else:
                proto = next(iter(cache.values()))
                model = RouterModel(proto.index, n_sub_slots=N_SLOTS,
                                    K=32, M=64, mesh=mesh)
                model._subs = {f: dict(s)
                               for f, s in proto._subs.items()}
                model._aux_refs = dict(proto._aux_refs)
                model._sub_mask = proto._sub_mask.copy()
                model._aux_mask = proto._aux_mask.copy()
                model._dense_row = dict(proto._dense_row)
                model._next_row = proto._next_row
                model._rowmap_host = proto._rowmap_host.copy()
                model._pool_host = proto._pool_host.copy()
                model.refresh()
            assert len(model._dense_row) >= 8
            cache[shape] = model
        return cache[shape]

    return get


# publish results memo: the parity matrix computes every (layout,
# shape, nbatch) result once; the layout-invariance test then compares
# ACROSS shapes without re-running any of them
_RESULTS: dict = {}


def _memo_publish(key, model, topics):
    if key not in _RESULTS:
        _RESULTS[key] = model.publish_batch(topics)
    return _RESULTS[key]


@pytest.mark.parametrize("shape", MESH_SHAPES,
                         ids=[f"dp{d}tp{t}" for d, t in MESH_SHAPES])
@pytest.mark.parametrize("nbatch", [128, 77], ids=["aligned", "uneven"])
def test_sharded_trie_parity_vs_single(single_model, sharded_trie_models,
                                       shape, nbatch):
    """The same 100k-filter set on the subscription-sharded trie must
    route identically to the flat single-device oracle at every tp
    split and batch geometry.  The sharded merge is shard-major, so
    matched/aux lists are compared as sets — the CONTENT contract;
    order stability across layouts is covered by
    test_sharded_layout_invariant_across_meshes and the S=1 bit-exact
    degeneracy below."""
    sharded = sharded_trie_models(shape)
    topics = _topics()[:nbatch]
    r1 = _memo_publish(("single", nbatch), single_model, topics)
    r2 = _memo_publish(("sharded", shape, nbatch), sharded, topics)
    assert [sorted(x) for x in r1[0]] == [sorted(x) for x in r2[0]]
    assert [sorted(x) for x in r1[1]] == [sorted(x) for x in r2[1]]
    assert [sorted(s) for s in r1[2]] == [sorted(s) for s in r2[2]]
    assert r1[3] == r2[3]
    # the dense broadcast filters fan out at high degree on the
    # sharded layout too (global fids feed the same rowmap/pool OR)
    bcast_rows = [j for j, t in enumerate(topics)
                  if t.startswith("broadcast/")]
    assert bcast_rows
    for j in bcast_rows:
        assert len(r2[2][j]) >= 90, len(r2[2][j])


@pytest.mark.parametrize("nbatch", [128, 77], ids=["aligned", "uneven"])
def test_sharded_layout_invariant_across_meshes(sharded_trie_models,
                                                nbatch):
    """With the shard count FIXED (S=4), every (dp, tp) placement of
    the stacked trie must return bit-identical results — which mesh
    axis owns the shard slices is a layout choice, never semantic."""
    topics = _topics()[:nbatch]
    results = [_memo_publish(("sharded", s, nbatch),
                             sharded_trie_models(s), topics)
               for s in MESH_SHAPES]
    for r in results[1:]:
        assert r == results[0]


def test_single_shard_degenerates_bit_identical():
    """S=1 is today's flat layout, bit-for-bit: identity fid
    translation, no-op second compact — matched order included. The
    property is structural (fid interleaving with S=1 is the identity,
    the second compact sees already-packed rows), so a compact filter
    set proves it; the 110k-scale sharded path is covered by the S=4
    fixtures above."""
    flat = RouterModel(TrieIndex(max_levels=8),
                       n_sub_slots=N_SLOTS, K=32, M=64)
    model = RouterModel(ShardedTrieIndex(1, max_levels=8),
                        n_sub_slots=N_SLOTS, K=32, M=64)
    for m in (flat, model):
        _populate(m, n=3_000, dense_fids=4, dense_degree=100)
    topics = _topics()[:77]
    r1 = flat.publish_batch(topics)
    r2 = model.publish_batch(topics)
    assert r1 == r2


def test_sharded_incremental_stays_per_shard_patches(sharded_trie_models):
    """Steady-state subscribe/unsubscribe on the sharded layout must
    stay per-shard element patches: upload_count (full [S, ...] stack
    re-uploads) may not grow, while the patch stream advances and the
    new routes serve (ISSUE 17 acceptance)."""
    model = sharded_trie_models((4, 2))
    ups, pats = model.upload_count, model.patch_count
    new = [(f"hotadd/dev{i}/+/m{i % 4}", (37 * i) % N_SLOTS)
           for i in range(12)]
    for f, s in new:
        model.subscribe(f, s)
    model.refresh()
    assert model.upload_count == ups, "subscribe forced a full re-upload"
    assert model.patch_count > pats
    # pad probes to the 128-bucket the parity matrix already compiled —
    # a 1-topic publish would otherwise compile a fresh B=64 program
    pad = ["no/subscribers/here"] * 116
    r = model.publish_batch(["hotadd/dev3/x/m3"] + pad[:127])
    assert "hotadd/dev3/+/m3" in r[0][0]
    # fids interleave shard-locally: every hot-added filter must decode
    # back through the global namespace
    assert sorted(model.publish_batch(
        [f"hotadd/dev{i}/y/m{i % 4}" for i in range(12)] + pad)[2][:12],
        key=len) != [[]] * 12
    pats2 = model.patch_count
    for f, s in new:
        model.unsubscribe(f, s)
    model.refresh()
    assert model.upload_count == ups, "unsubscribe forced a full re-upload"
    assert model.patch_count > pats2
    assert model.publish_batch(["hotadd/dev3/x/m3"] + pad)[0][0] == []


def test_full_stack_serving_on_mesh():
    """broker + pipeline + kernel on a 4x2 mesh, real MQTT clients over
    TCP: deliveries must come off mesh-sharded kernel launches."""
    import jax

    from emqx_tpu.app import BrokerApp
    from emqx_tpu.broker.server import BrokerServer
    from emqx_tpu.mqtt.client import MqttClient

    assert len(jax.devices()) >= 8
    mesh = make_mesh(8, shape=(4, 2))
    model = RouterModel(TrieIndex(max_levels=8), n_sub_slots=N_SLOTS,
                        K=32, M=64, mesh=mesh)
    app = BrokerApp(router_model=model)
    app.pipeline.min_device_batch = 0      # every batch rides the mesh

    async def main():
        server = BrokerServer(port=0, app=app)
        await server.start()
        subs = [MqttClient(port=server.port, clientid=f"ms{i}")
                for i in range(4)]
        for i, s in enumerate(subs):
            await s.connect()
            await s.subscribe(f"grid/{i}/+", qos=0)
        pub = MqttClient(port=server.port, clientid="mp")
        await pub.connect()
        launches0 = model.launch_count
        for r in range(3):
            for i in range(4):
                await pub.publish(f"grid/{i}/cell{r}",
                                  f"{r}:{i}".encode(), qos=0)
        for i, s in enumerate(subs):
            got = sorted([(await s.recv(timeout=60)).payload
                          for _ in range(3)])
            assert got == sorted(f"{r}:{i}".encode() for r in range(3))
        assert model.launch_count > launches0, "mesh kernel never launched"
        for c in subs + [pub]:
            await c.close()
        await server.stop()

    asyncio.run(main())


@pytest.mark.parametrize("stage", ["submit", "collect"])
@pytest.mark.parametrize("layout", ["replicated", "sharded"])
def test_device_loss_fails_over_to_host(stage, layout):
    """Device loss mid-serving (VERDICT weak #7): when the mesh kernel
    dies — at launch or at collect — the broker serves the batch from
    the host oracle instead of dropping it, counts the failover, and
    keeps delivering.  Both trie layouts: the failover contract may not
    depend on whether the dead kernel held a replicated or a
    subscription-sharded trie."""
    import jax

    from emqx_tpu.app import BrokerApp
    from emqx_tpu.broker.server import BrokerServer
    from emqx_tpu.mqtt.client import MqttClient

    assert len(jax.devices()) >= 8
    mesh = make_mesh(8, shape=(4, 2))
    index = (ShardedTrieIndex(TRIE_SHARDS, max_levels=8)
             if layout == "sharded" else TrieIndex(max_levels=8))
    model = RouterModel(index, n_sub_slots=N_SLOTS,
                        K=32, M=64, mesh=mesh)
    app = BrokerApp(router_model=model)
    app.pipeline.min_device_batch = 0      # force the device path

    async def main():
        server = BrokerServer(port=0, app=app)
        await server.start()
        sub = MqttClient(port=server.port, clientid="dl-sub")
        await sub.connect()
        await sub.subscribe("loss/+", qos=0)
        pub = MqttClient(port=server.port, clientid="dl-pub")
        await pub.connect()

        # healthy first: the device path serves
        await pub.publish("loss/a", b"pre", qos=0)
        assert (await sub.recv(timeout=60)).payload == b"pre"

        # kill the device: every subsequent launch (or collect) raises
        def dead(*a, **k):
            raise RuntimeError("simulated device loss (ICI reset)")

        if stage == "submit":
            model.publish_batch_submit = dead
        else:
            model.publish_batch_collect = dead

        for i in range(3):
            await pub.publish(f"loss/{i}", b"post%d" % i, qos=0)
        got = sorted([(await sub.recv(timeout=60)).payload
                      for _ in range(3)])
        assert got == [b"post0", b"post1", b"post2"]
        assert app.broker.metrics.val("messages.device_failover") > 0
        await pub.disconnect()
        await sub.disconnect()
        await server.stop()

    asyncio.run(main())
