"""Redis connector (RESP client + MiniRedis backend), redis authn/authz —
the emqx_connector_redis / emqx_authn_redis / emqx_authz_redis suites,
driven over a real socket against the protocol-faithful mini server."""

import pytest

from emqx_tpu.access.authn import AuthnChain
from emqx_tpu.access.authz import Authz
from emqx_tpu.access.hashing import HashSpec, gen_salt, hash_password
from emqx_tpu.access.redis_backends import (
    RedisAclSource, RedisAuthnProvider, render_cmd,
)
from emqx_tpu.connector.redis import (
    MiniRedis, RedisClient, RedisConnector, RedisError,
)


@pytest.fixture()
def server():
    s = MiniRedis().start()
    yield s
    s.stop()


def test_resp_roundtrip_and_types(server):
    c = RedisClient(port=server.port)
    assert c.command(["PING"]) == "PONG"
    assert c.command(["SET", "k", "v"]) == "OK"
    assert c.command(["GET", "k"]) == b"v"
    assert c.command(["GET", "missing"]) is None
    assert c.command(["HSET", "h", "f1", "x", "f2", "y"]) == 2
    assert c.command(["HGET", "h", "f1"]) == b"x"
    got = c.command(["HGETALL", "h"])
    assert dict(zip(got[::2], got[1::2])) == {b"f1": b"x", b"f2": b"y"}
    assert c.command(["SADD", "s", "a", "b"]) == 2
    assert c.command(["SMEMBERS", "s"]) == [b"a", b"b"]
    assert c.command(["DEL", "k"]) == 1
    with pytest.raises(RedisError):
        c.command(["NOPE"])
    c.close()


def test_auth_required():
    s = MiniRedis(password="hunter2").start()
    try:
        bad = RedisClient(port=s.port)
        with pytest.raises(RedisError):
            bad.command(["GET", "k"])
        good = RedisClient(port=s.port, password="hunter2")
        assert good.command(["PING"]) == "PONG"
        good.close()
        bad.close()
    finally:
        s.stop()


def test_connector_resource_surface(server):
    conn = RedisConnector(port=server.port)
    conn.on_start({})
    assert conn.on_health_check()
    assert conn.on_query({"cmd": ["SET", "a", "1"]}) == "OK"
    assert conn.on_query(["GET", "a"]) == b"1"
    conn.on_stop()


def test_redis_authn_provider(server):
    spec = HashSpec(name="sha256", salt_position="prefix")
    salt = gen_salt(spec)
    stored = hash_password(spec, salt, b"s3cret")
    admin = RedisClient(port=server.port)
    admin.command(["HSET", "mqtt_user:alice",
                   "password_hash", stored.decode(),
                   "salt", salt.decode(), "is_superuser", "true"])
    chain = AuthnChain([RedisAuthnProvider(
        RedisClient(port=server.port), hash_spec=spec)])
    ok = chain.authenticate({"username": "alice", "password": "s3cret"})
    assert ok[0] == "ok" and ok[1]["is_superuser"]
    bad = chain.authenticate({"username": "alice", "password": "wrong"})
    assert bad[0] == "error"
    # unknown user → ignore → chain default deny
    miss = chain.authenticate({"username": "bob", "password": "x"})
    assert miss[0] == "error"
    admin.close()


def test_redis_acl_source(server):
    admin = RedisClient(port=server.port)
    admin.command(["HSET", "mqtt_acl:dev1",
                   "sensors/+/temp", "subscribe",
                   "cmd/dev1", "all"])
    authz = Authz([RedisAclSource(RedisClient(port=server.port))],
                  no_match="deny")
    ci = {"clientid": "c", "username": "dev1"}
    assert authz.authorize(ci, "subscribe", "sensors/9/temp") == "allow"
    assert authz.authorize(ci, "publish", "cmd/dev1") == "allow"
    assert authz.authorize(ci, "publish", "sensors/9/temp") == "deny"
    assert authz.authorize(ci, "subscribe", "other") == "deny"
    admin.close()


def test_render_cmd_placeholders():
    assert render_cmd(["HGETALL", "u:${username}:${clientid}"],
                      {"username": "a", "clientid": "c1"}) == \
        ["HGETALL", "u:a:c1"]


def test_redis_bridge_end_to_end(server):
    """Rule-engine → redis bridge → MiniRedis (the emqx_ee_bridge_redis
    path over a real socket)."""
    from emqx_tpu.app import BrokerApp
    from emqx_tpu.core.message import Message

    app = BrokerApp()
    bridge = app.bridges.create(
        "redis", "sink", RedisConnector(port=server.port),
        {"command_template": ["SET", "last:${topic}", "${payload}"]},
        batch_size=1)
    app.rules.create_rule(
        id="r-redis",
        sql='SELECT topic, payload FROM "t/#"',
        actions=[{"function": "redis:sink"}])
    app.cm.dispatch(app.broker.publish(
        Message(topic="t/2", payload=b"hello-redis2")))
    bridge.worker.flush()
    probe = RedisClient(port=server.port)
    assert probe.command(["GET", "last:t/2"]) == b"hello-redis2"
    # error path: a command MiniRedis rejects counts as failed, not stuck
    app.bridges.delete("redis:sink")
    app.rules.delete_rule("r-redis")
    bad = app.bridges.create(
        "redis", "bad", RedisConnector(port=server.port),
        {"command_template": ["LPUSH", "q", "${payload}"]},
        batch_size=1, max_retries=0)
    bad.send({"topic": "t/3", "payload": "x"})
    bad.worker.flush()
    assert bad.worker.metrics["failed"] >= 1 or \
        bad.worker.metrics["success"] == 0
    probe.close()


def test_client_reconnects_after_server_restart():
    """A stale pooled connection must not fail a request against a
    healthy backend (one transparent reconnect)."""
    s1 = MiniRedis().start()
    c = RedisClient(port=s1.port)
    assert c.command(["PING"]) == "PONG"
    port = s1.port
    s1.stop()
    s2 = MiniRedis(host="127.0.0.1", port=port).start()
    try:
        assert c.command(["PING"]) == "PONG"     # retried on fresh conn
    finally:
        c.close()
        s2.stop()


def test_funcs_fix_regressions():
    from emqx_tpu.rules.funcs import FUNCS

    assert FUNCS["float2str"](100, 0) == "100"
    assert FUNCS["float2str"](1.50, 2) == "1.5"
    FUNCS["kv_store_put"]("zero", 0)
    assert FUNCS["kv_store_del"]("zero") is None
    # format_date honours the offset argument
    utc = FUNCS["format_date"]("second", "+00:00", "%H", 3600 * 5)
    plus8 = FUNCS["format_date"]("second", "+08:00", "%H", 3600 * 5)
    assert (int(plus8) - int(utc)) % 24 == 8


def test_nonidempotent_command_not_resent_after_reply_drop():
    """A write command whose connection dies AFTER the request was
    written must surface the error instead of silently re-executing
    (ADVICE: LPUSH/INCR could run twice server-side)."""
    import socket as socket_mod

    from emqx_tpu.connector.redis import RedisClient

    # server that answers the first command on each connection (so the
    # client holds an ESTABLISHED pooled connection), then drops the
    # second one after reading it but before replying — the ambiguous
    # failure window where the request may have executed server-side
    srv = socket_mod.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(4)
    port = srv.getsockname()[1]
    import threading

    incr_seen = {"n": 0}

    def serve():
        while True:
            try:
                c, _ = srv.accept()
            except OSError:
                return
            try:
                data = c.recv(4096)              # first command: reply
                if b"INCR" in data:
                    incr_seen["n"] += 1
                c.sendall(b"+PONG\r\n")
                data = c.recv(4096)              # second: read, drop
                if b"INCR" in data:
                    incr_seen["n"] += 1
            except OSError:
                pass
            c.close()

    th = threading.Thread(target=serve, daemon=True)
    th.start()
    try:
        cli = RedisClient("127.0.0.1", port, timeout_s=2.0)
        assert cli.command(["PING"]) == "PONG"   # connection established
        with pytest.raises((OSError, ConnectionError)):
            cli.command(["INCR", "counter"])
        # exactly one INCR reached a server socket — no blind resend on
        # a fresh connection
        assert incr_seen["n"] == 1
    finally:
        srv.close()
