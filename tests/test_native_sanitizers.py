"""ASan/TSan runs of the C++ connection host (SURVEY §5: the build's
planned stand-in for BEAM's share-nothing guarantees is C++-side
sanitizers — the host has a poll thread plus send/close entry points
callable from any thread, and an off-thread housekeeping path).

Each case compiles a sanitized variant of ``host.cc`` and drives it in a
SUBPROCESS with the sanitizer runtime LD_PRELOADed (a dlopen'd sanitized
.so needs its runtime loaded first). The driver exercises: accept,
byte-dribbled framing, concurrent cross-thread sends, close-during-send
races, and teardown. Any sanitizer report fails the run (halt_on_error)."""

import os
import subprocess
import sys

import pytest

_SAN_LIBS = {}
for _name, _lib in (("address", "libasan.so"), ("thread", "libtsan.so")):
    try:
        p = subprocess.run(["g++", f"-print-file-name={_lib}"],
                           capture_output=True, text=True).stdout.strip()
        if p and os.path.exists(p):
            _SAN_LIBS[_name] = p
    except OSError:
        pass


DRIVER = r"""
import os, socket, struct, sys, threading, time
sys.path.insert(0, %(repo)r)
from emqx_tpu import native

host = native.NativeHost(port=0, max_size=1 << 16)
N = 8

def connect_and_dribble(i):
    s = socket.create_connection(("127.0.0.1", host.port))
    # minimal MQTT CONNECT, dribbled byte-by-byte to stress the framer
    vh = b"\x00\x04MQTT\x04\x02\x00\x3c" + struct.pack(">H", 4) + b"c%%03d" %% i
    pkt = bytes([0x10, len(vh)]) + vh
    for b in pkt:
        s.sendall(bytes([b]))
        if i %% 3 == 0:
            time.sleep(0.001)
    return s

socks = []
conns = []
frames = 0
deadline = time.time() + 15

t_conns = [threading.Thread(target=lambda i=i: socks.append(
    connect_and_dribble(i))) for i in range(N)]
for t in t_conns: t.start()
for t in t_conns: t.join()

stop = threading.Event()
def blaster():
    # cross-thread sends against whatever connections exist (the
    # threading contract under test: send/close from non-poll threads)
    while not stop.is_set():
        for c in list(conns):
            host.send(c, b"\xd0\x00")       # PINGRESP
        time.sleep(0.0005)
blast = threading.Thread(target=blaster)
blast.start()

while frames < N and time.time() < deadline:
    for kind, conn, payload in host.poll(50):
        if kind == native.EV_OPEN:
            conns.append(conn)
        elif kind == native.EV_FRAME:
            frames += 1
            host.send(conn, b"\x20\x02\x00\x00")   # CONNACK
assert frames == N, f"framed {frames}/{N}"

# close-during-send race: keep the blaster running while closing
for c in conns[: N // 2]:
    host.close_conn(c)
time.sleep(0.05)
stop.set(); blast.join()
for s in socks:
    try: s.close()
    except OSError: pass
# drain close events, then teardown with the poll loop stopped
for _ in range(10):
    list(host.poll(10))
host.destroy()
print("SANITIZED-RUN-OK")
"""


@pytest.mark.parametrize("sanitizer", ["address", "thread"])
def test_host_cc_sanitized(sanitizer, tmp_path):
    if sanitizer not in _SAN_LIBS:
        pytest.skip(f"{sanitizer} sanitizer runtime not available")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {
        **os.environ,
        "EMQX_NATIVE_SANITIZE": sanitizer,
        "LD_PRELOAD": _SAN_LIBS[sanitizer],
        "ASAN_OPTIONS": "halt_on_error=1:detect_leaks=0",
        # leak detection off: the PYTHON interpreter under LD_PRELOAD
        # reports its own arena allocs; host.cc still gets full
        # use-after-free/overflow/race coverage
        "TSAN_OPTIONS": "halt_on_error=1:report_signal_unsafe=0",
    }
    proc = subprocess.run(
        [sys.executable, "-c", DRIVER % {"repo": repo}],
        capture_output=True, text=True, env=env, timeout=120)
    assert "SANITIZED-RUN-OK" in proc.stdout, (
        f"rc={proc.returncode}\nstdout={proc.stdout[-2000:]}\n"
        f"stderr={proc.stderr[-4000:]}")
    for marker in ("ERROR: AddressSanitizer", "WARNING: ThreadSanitizer",
                   "ERROR: ThreadSanitizer"):
        assert marker not in proc.stderr, proc.stderr[-4000:]
