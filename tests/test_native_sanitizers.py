"""ASan/TSan runs of the C++ connection host (SURVEY §5: the build's
planned stand-in for BEAM's share-nothing guarantees is C++-side
sanitizers — the host has a poll thread plus send/close entry points
callable from any thread, and an off-thread housekeeping path).

Each case compiles a sanitized variant of ``host.cc`` and drives it in a
SUBPROCESS with the sanitizer runtime LD_PRELOADed (a dlopen'd sanitized
.so needs its runtime loaded first). The driver exercises: accept,
byte-dribbled framing, concurrent cross-thread sends, close-during-send
races, and teardown. Any sanitizer report fails the run (halt_on_error)."""

import os
import subprocess
import sys

import pytest

_SAN_LIBS = {}
for _name, _lib in (("address", "libasan.so"), ("thread", "libtsan.so")):
    try:
        p = subprocess.run(["g++", f"-print-file-name={_lib}"],
                           capture_output=True, text=True).stdout.strip()
        if p and os.path.exists(p):
            _SAN_LIBS[_name] = p
    except OSError:
        pass


DRIVER = r"""
import os, socket, struct, sys, threading, time
sys.path.insert(0, %(repo)r)
from emqx_tpu import native

host = native.NativeHost(port=0, max_size=1 << 16)
N = 8

def connect_and_dribble(i):
    s = socket.create_connection(("127.0.0.1", host.port))
    # minimal MQTT CONNECT, dribbled byte-by-byte to stress the framer
    vh = b"\x00\x04MQTT\x04\x02\x00\x3c" + struct.pack(">H", 4) + b"c%%03d" %% i
    pkt = bytes([0x10, len(vh)]) + vh
    for b in pkt:
        s.sendall(bytes([b]))
        if i %% 3 == 0:
            time.sleep(0.001)
    return s

socks = []
conns = []
frames = 0
deadline = time.time() + 15

t_conns = [threading.Thread(target=lambda i=i: socks.append(
    connect_and_dribble(i))) for i in range(N)]
for t in t_conns: t.start()
for t in t_conns: t.join()

stop = threading.Event()
def blaster():
    # cross-thread sends against whatever connections exist (the
    # threading contract under test: send/close from non-poll threads)
    while not stop.is_set():
        for c in list(conns):
            host.send(c, b"\xd0\x00")       # PINGRESP
        time.sleep(0.0005)
blast = threading.Thread(target=blaster)
blast.start()

while frames < N and time.time() < deadline:
    for kind, conn, payload in host.poll(50):
        if kind == native.EV_OPEN:
            conns.append(conn)
        elif kind == native.EV_FRAME:
            frames += 1
            host.send(conn, b"\x20\x02\x00\x00")   # CONNACK
assert frames == N, f"framed {frames}/{N}"

# close-during-send race: keep the blaster running while closing
for c in conns[: N // 2]:
    host.close_conn(c)
time.sleep(0.05)
stop.set(); blast.join()
for s in socks:
    try: s.close()
    except OSError: pass
# drain close events, then teardown with the poll loop stopped
for _ in range(10):
    list(host.poll(10))
host.destroy()
print("SANITIZED-RUN-OK")
"""


# Round-4 fast-path coverage: enable_fast + sub/shared/punt control
# ops racing the poll thread, qos0/1 publish fan-out in C++ (TryFast /
# DeliverTo / TryFastPuback), native PUBACK consumption, permit churn,
# and close-during-delivery.
DRIVER_FASTPATH = r"""
import socket, struct, sys, threading, time
sys.path.insert(0, %(repo)r)
from emqx_tpu import native

host = native.NativeHost(port=0, max_size=1 << 16)

def mqtt_connect(cid):
    vh = b"\x00\x04MQTT\x04\x02\x00\x3c" + struct.pack(">H", len(cid)) + cid
    return bytes([0x10, len(vh)]) + vh

def mqtt_publish(topic, payload, qos=0, pid=0):
    body = struct.pack(">H", len(topic)) + topic
    if qos:
        body += struct.pack(">H", pid)
    body += payload
    return bytes([0x30 | (qos << 1), len(body)]) + body

socks = [socket.create_connection(("127.0.0.1", host.port))
         for _ in range(3)]
ids = []
for i, s in enumerate(socks):
    s.sendall(mqtt_connect(b"f%%d" %% i))
deadline = time.time() + 15
framed = 0
while (len(ids) < 3 or framed < 3) and time.time() < deadline:
    for kind, conn, payload in host.poll(50):
        if kind == native.EV_OPEN:
            ids.append(conn)
        elif kind == native.EV_FRAME:
            framed += 1
            host.send(conn, b"\x20\x02\x00\x00")
assert len(ids) == 3 and framed == 3, (ids, framed)
sub1, sub2, pub = ids       # event order == connect order (one poller)

for c in ids:
    host.enable_fast(c, 4, 64)
host.sub_add(sub1, "fp/+", qos=1)
host.shared_add(7, sub2, "fp/+", 1, 0)  # qos1: acker exercises TryFastPuback
host.sub_add(1 << 50, "punted/#", 0, native.SUB_PUNT)
host.permit(pub, "fp/x")
host.permit(pub, "punted/y")

stop = threading.Event()
def control_churn():
    # thread-safe control plane hammering the poll thread's tables
    # (conn_idle_ms is deliberately NOT here: it is poll-thread-only —
    # TSan caught its conns_ walk racing Drop's erase when this driver
    # originally called it cross-thread)
    j = 0
    while not stop.is_set():
        host.sub_add(sub1, "churn/%%d" %% (j %% 7), 0, 0)
        host.sub_del(sub1, "churn/%%d" %% ((j + 3) %% 7))
        host.stats()
        if j %% 50 == 17:
            host.permits_flush()
            host.permit(pub, "fp/x")
            host.permit(pub, "punted/y")   # keep the punt-marker path live
        j += 1
        time.sleep(0.0002)
ctl = threading.Thread(target=control_churn)
ctl.start()

time.sleep(0.2)   # let the ops apply
N_MSG = 400
def blaster():
    for k in range(N_MSG):
        qos = k & 1
        socks[2].sendall(mqtt_publish(b"fp/x", b"p%%03d" %% k, qos,
                                      1 + (k %% 100)))
        socks[2].sendall(mqtt_publish(b"punted/y", b"q", 0, 0))
        if k == N_MSG // 2:
            socks[0].close()          # close a subscriber mid-delivery
        time.sleep(0.0002)
bl = threading.Thread(target=blaster)
bl.start()

# subscriber 2 acks native qos1 deliveries; the poll loop keeps running
def acker():
    buf = b""
    socks[1].settimeout(0.2)
    while not stop.is_set():
        try:
            chunk = socks[1].recv(4096)
        except (TimeoutError, OSError):
            continue
        if not chunk:
            return
        buf += chunk
        while len(buf) >= 2:
            ln = buf[1]
            if ln & 0x80 or len(buf) < 2 + ln:
                break
            frame, buf = buf[: 2 + ln], buf[2 + ln:]
            if frame[0] >> 4 == 3 and (frame[0] >> 1) & 3 == 1:
                tlen = (frame[2] << 8) | frame[3]
                pid = (frame[4 + tlen] << 8) | frame[5 + tlen]
                try:
                    socks[1].sendall(bytes([0x40, 2, pid >> 8, pid & 0xFF]))
                except OSError:
                    return
ack = threading.Thread(target=acker)
ack.start()

punts = 0
deadline = time.time() + 20
while time.time() < deadline:
    for kind, conn, payload in host.poll(20):
        if kind == native.EV_FRAME:
            punts += 1            # punted/# frames come up verbatim
    host.conn_idle_ms(sub2)       # poll-thread-only query, on-thread here
    st = host.stats()
    # flush-to-re-permit gaps legitimately punt some fp/x messages;
    # this is a sanitizer drive, not a counting test — exit once every
    # exercised path has clearly run
    if (st["fast_in"] > N_MSG // 2 and st["shared_dispatch"] > 0
            and st["punts"] > 0 and st["native_acks"] > 0):
        break
bl.join()
time.sleep(0.3)
stop.set(); ctl.join(); ack.join()
st = host.stats()
assert st["fast_in"] > 0 and st["fast_out"] > 0, st
assert st["shared_dispatch"] > 0, st
assert st["native_acks"] > 0, st       # TryFastPuback ran
assert st["punts"] > 0, st             # the kSubPunt branch ran
assert punts > 0, "punted frames never forwarded"
for s in socks[1:]:
    try: s.close()
    except OSError: pass
for _ in range(10):
    list(host.poll(10))
host.destroy()
print("SANITIZED-RUN-OK", st)
"""


# Round-5 device-lane coverage: EV_LANE records from TryFast's park
# path, lane_deliver blobs applied from a foreign thread (Enqueue →
# ApplyOp → LaneDeliver fan-out incl. the punt branch), set_lane
# toggles draining parked frames mid-traffic, and lane_backlog reads
# racing the poll thread.
DRIVER_LANE = r"""
import socket, struct, sys, threading, time
sys.path.insert(0, %(repo)r)
from emqx_tpu import native

host = native.NativeHost(port=0, max_size=1 << 16)

def mqtt_connect(cid):
    vh = b"\x00\x04MQTT\x04\x02\x00\x3c" + struct.pack(">H", len(cid)) + cid
    return bytes([0x10, len(vh)]) + vh

def mqtt_publish(topic, payload, qos=0, pid=0):
    body = struct.pack(">H", len(topic)) + topic
    if qos:
        body += struct.pack(">H", pid)
    body += payload
    return bytes([0x30 | (qos << 1), len(body)]) + body

socks = [socket.create_connection(("127.0.0.1", host.port))
         for _ in range(2)]
ids = []
for i, s in enumerate(socks):
    s.sendall(mqtt_connect(b"l%%d" %% i))
deadline = time.time() + 15
framed = 0
while (len(ids) < 2 or framed < 2) and time.time() < deadline:
    for kind, conn, payload in host.poll(50):
        if kind == native.EV_OPEN:
            ids.append(conn)
        elif kind == native.EV_FRAME:
            framed += 1
            host.send(conn, b"\x20\x02\x00\x00")
assert len(ids) == 2 and framed == 2, (ids, framed)
sub, pub = ids

for c in ids:
    host.enable_fast(c, 4, 64)
host.sub_add(sub, "ln/+", 1, 0)
host.permit(pub, "ln/x")
host.set_lane(True)

stop = threading.Event()
lane_reqs = []
req_lock = threading.Lock()

def pump():
    # foreign-thread responder: builds blobs and enqueues them while
    # the poll thread keeps parking/draining entries
    k = 0
    while not stop.is_set():
        with req_lock:
            batch, lane_reqs[:] = lane_reqs[:], []
        if not batch:
            time.sleep(0.001)
            continue
        parts = [struct.pack("<I", len(batch))]
        for seq, topic in batch:
            k += 1
            if k %% 7 == 3:
                parts.append(struct.pack("<QBH", seq, 1, 0))  # punt
            else:
                f = b"ln/+"
                parts.append(struct.pack("<QBH", seq, 0, 1))
                parts.append(struct.pack("<H", len(f)))
                parts.append(f)
        host.lane_deliver(b"".join(parts))
pp = threading.Thread(target=pump)
pp.start()

def control_churn():
    j = 0
    while not stop.is_set():
        host.sub_add(sub, "churn/%%d" %% (j %% 5), 0, 0)
        host.sub_del(sub, "churn/%%d" %% ((j + 2) %% 5))
        host.lane_backlog()
        host.stats()
        if j %% 97 == 41:
            host.set_lane(False)   # drain parked frames mid-traffic
            host.set_lane(True)
            host.permit(pub, "ln/x")
        j += 1
        time.sleep(0.0002)
ctl = threading.Thread(target=control_churn)
ctl.start()

time.sleep(0.2)
N_MSG = 500
def blaster():
    for k in range(N_MSG):
        socks[1].sendall(mqtt_publish(b"ln/x", b"p%%03d" %% k, k & 1,
                                      1 + (k %% 100)))
        time.sleep(0.0002)
bl = threading.Thread(target=blaster)
bl.start()

drained = 0
deadline = time.time() + 20
while time.time() < deadline:
    for kind, conn, payload in host.poll(20):
        if kind == 4:           # EV_LANE
            with req_lock:
                lane_reqs.append((conn, payload.decode()))
        elif kind == native.EV_FRAME:
            drained += 1        # punted/drained frames come up verbatim
    st = host.stats()
    if (st["lane_in"] > N_MSG // 4 and st["lane_out"] > 0
            and st["lane_punts"] > 0):
        break
bl.join()
time.sleep(0.3)
stop.set(); ctl.join(); pp.join()
st = host.stats()
assert st["lane_in"] > 0 and st["lane_out"] > 0, st
assert st["lane_punts"] > 0, st
for s in socks:
    try: s.close()
    except OSError: pass
for _ in range(10):
    list(host.poll(10))
host.destroy()
print("SANITIZED-RUN-OK", st)
"""


# Round-7 WebSocket coverage: the RFC6455 plane (ws.h + host.cc) under
# the sanitizers — upgrade handshakes (incl. a rejected one), masked
# frame decode with in-place unmasking, byte-dribbled and fragmented
# frames, ping/pong, close echo, fast-path delivery ONTO a ws conn
# (egress wrapping), cross-thread sends, and close-during-traffic.
DRIVER_WS = r"""
import os, socket, struct, sys, threading, time
sys.path.insert(0, %(repo)r)
from emqx_tpu import native

host = native.NativeHost(port=0, max_size=1 << 16)
wsp = host.listen_ws()

def mask(payload, key=b"\x11\x22\x33\x44"):
    return bytes(b ^ key[i %% 4] for i, b in enumerate(payload))

def frame(op, payload, fin=True, key=b"\x11\x22\x33\x44"):
    h = bytearray([(0x80 if fin else 0) | op])
    n = len(payload)
    if n < 126:
        h.append(0x80 | n)
    else:
        h.append(0x80 | 126); h += struct.pack(">H", n)
    return bytes(h) + key + mask(payload, key)

def upgrade(s, dribble=False):
    req = (b"GET /mqtt HTTP/1.1\r\nHost: x\r\nUpgrade: websocket\r\n"
           b"Connection: Upgrade\r\nSec-WebSocket-Key: AAAAAAAAAAAAAAAAAAAAAA==\r\n"
           b"Sec-WebSocket-Version: 13\r\n\r\n")
    if dribble:
        for i in range(0, len(req), 7):
            s.sendall(req[i:i + 7]); time.sleep(0.0005)
    else:
        s.sendall(req)
    buf = b""
    while b"\r\n\r\n" not in buf:
        buf += s.recv(4096)
    assert b"101" in buf, buf

def mqtt_connect(cid):
    vh = b"\x00\x04MQTT\x04\x02\x00\x3c" + struct.pack(">H", len(cid)) + cid
    return bytes([0x10, len(vh)]) + vh

def mqtt_publish(topic, payload, qos=0, pid=0):
    body = struct.pack(">H", len(topic)) + topic
    if qos:
        body += struct.pack(">H", pid)
    body += payload
    return bytes([0x30 | (qos << 1), len(body)]) + body

socks = [socket.create_connection(("127.0.0.1", wsp)) for _ in range(3)]
ids = []
framed = 0
deadline = time.time() + 15

def setup():
    for i, s in enumerate(socks):
        upgrade(s, dribble=(i == 0))
        s.sendall(frame(0x2, mqtt_connect(b"w%%d" %% i)))
su = threading.Thread(target=setup)
su.start()
while (len(ids) < 3 or framed < 3) and time.time() < deadline:
    for kind, conn, payload in host.poll(50):
        if kind == native.EV_OPEN:
            assert payload.startswith(b"ws:"), payload
            ids.append(conn)
        elif kind == native.EV_FRAME:
            framed += 1
            host.send(conn, b"\x20\x02\x00\x00")   # CONNACK (host wraps)
su.join()
assert len(ids) == 3 and framed == 3, (ids, framed)
sub, pub, extra = ids

for c in ids:
    host.enable_fast(c, 4, 64)
host.sub_add(sub, "w/+", 1, 0)
host.permit(pub, "w/x")

stop = threading.Event()
def control_churn():
    j = 0
    while not stop.is_set():
        host.sub_add(sub, "churn/%%d" %% (j %% 5), 0, 0)
        host.sub_del(sub, "churn/%%d" %% ((j + 2) %% 5))
        host.stats()
        for c in list(ids):
            host.send(c, b"\xd0\x00")              # cross-thread PINGRESP
        j += 1
        time.sleep(0.0003)
ctl = threading.Thread(target=control_churn)
ctl.start()

time.sleep(0.2)
N_MSG = 300
def blaster():
    for k in range(N_MSG):
        pkt = mqtt_publish(b"w/x", b"p%%03d" %% k, k & 1, 1 + (k %% 100))
        if k %% 5 == 0:
            # fragmented: binary FIN=0 + continuation FIN=1
            a, b = pkt[:4], pkt[4:]
            socks[1].sendall(frame(0x2, a, fin=False) + frame(0x0, b))
        elif k %% 7 == 0:
            socks[1].sendall(frame(0x9, b"hb"))     # ping mid-stream
            socks[1].sendall(frame(0x2, pkt))
        else:
            socks[1].sendall(frame(0x2, pkt))
        if k == N_MSG // 2:
            socks[2].sendall(frame(0x8, struct.pack(">H", 1000)))  # close
        time.sleep(0.0003)
bl = threading.Thread(target=blaster)
bl.start()

# subscriber acks native qos1 deliveries THROUGH the ws codec
def acker():
    buf = b""
    socks[0].settimeout(0.2)
    while not stop.is_set():
        try:
            chunk = socks[0].recv(8192)
        except (TimeoutError, OSError):
            continue
        if not chunk:
            return
        buf += chunk
        # minimal server-frame walk (unmasked, small payloads)
        while len(buf) >= 2:
            n = buf[1] & 0x7F
            off = 2
            if n == 126:
                if len(buf) < 4: break
                n = struct.unpack(">H", buf[2:4])[0]; off = 4
            if len(buf) < off + n: break
            payload, buf = buf[off:off + n], buf[off + n:]
            if payload and payload[0] >> 4 == 3 and (payload[0] >> 1) & 3 == 1:
                tlen = (payload[2] << 8) | payload[3]
                pid = (payload[4 + tlen] << 8) | payload[5 + tlen]
                try:
                    socks[0].sendall(frame(0x2, bytes([0x40, 2, pid >> 8, pid & 0xFF])))
                except OSError:
                    return
ack = threading.Thread(target=acker)
ack.start()

deadline = time.time() + 20
while time.time() < deadline:
    list(host.poll(20))
    st = host.stats()
    if (st["fast_in"] > N_MSG // 2 and st["ws_pings"] > 0
            and st["ws_closes"] > 0 and st["native_acks"] > 0):
        break
bl.join()
time.sleep(0.3)
stop.set(); ctl.join(); ack.join()
st = host.stats()
assert st["ws_handshakes"] == 3, st
assert st["fast_in"] > 0 and st["fast_out"] > 0, st
assert st["ws_pings"] > 0 and st["ws_closes"] > 0, st
assert st["native_acks"] > 0, st
# a rejected upgrade exercises the 400 path under the sanitizer too
bad = socket.create_connection(("127.0.0.1", wsp))
bad.settimeout(0.2)
bad.sendall(b"GET /other HTTP/1.1\r\nHost: x\r\nUpgrade: websocket\r\n"
            b"Connection: Upgrade\r\nSec-WebSocket-Key: A==\r\n\r\n")
for _ in range(20):
    list(host.poll(10))
    try:
        if b"400" in bad.recv(4096):
            break
    except (TimeoutError, OSError):
        pass
bad.close()
for s in socks:
    try: s.close()
    except OSError: pass
for _ in range(10):
    list(host.poll(10))
host.destroy()
print("SANITIZED-RUN-OK", st)
"""


# Round-8 telemetry-plane coverage (ISSUE 3 satellite): histogram
# export + flight-recorder dumps under load, with set_trace /
# set_telemetry toggles racing the poll thread from a control thread,
# and a protocol-error teardown dumping the recorder mid-traffic.
DRIVER_TELEMETRY = r"""
import socket, struct, sys, threading, time
sys.path.insert(0, %(repo)r)
from emqx_tpu import native

host = native.NativeHost(port=0, max_size=4096)

def connect(cid):
    s = socket.create_connection(("127.0.0.1", host.port))
    vh = b"\x00\x04MQTT\x04\x02\x00\x3c" + struct.pack(">H", len(cid)) + cid
    s.sendall(bytes([0x10, len(vh)]) + vh)
    return s

def pub_frame(topic, payload, qos=0, pid=0):
    vh = struct.pack(">H", len(topic)) + topic
    if qos:
        vh += struct.pack(">H", pid)
    vh += payload
    return bytes([0x30 | (qos << 1), len(vh)]) + vh

socks = [connect(b"t%%02d" %% i) for i in range(6)]
conns = []
deadline = time.time() + 15
while len(conns) < 6 and time.time() < deadline:
    for kind, conn, payload in host.poll(20):
        if kind == native.EV_OPEN:
            conns.append(conn)
assert len(conns) == 6, conns
pub_id, sub_id = conns[0], conns[1]
host.enable_fast(pub_id, 4)
host.sub_add(sub_id, "tele/t", qos=1)
host.permit(pub_id, "tele/t")
list(host.poll(20))

stop = threading.Event()
def toggler():
    # cross-thread control ops racing the poll thread (the contract
    # under test): trace punts flip and the telemetry master switch
    # cycles while publishes flow
    i = 0
    while not stop.is_set():
        host.set_trace(conns[2 + (i %% 4)], i %% 2 == 0)
        if i %% 7 == 0:
            host.set_telemetry(i %% 14 != 0, slow_ack_ms=0)
        i += 1
        time.sleep(0.002)
tog = threading.Thread(target=toggler)
tog.start()

tele_records = 0
flights = 0
hist_deltas = 0
for burst in range(30):
    for i in range(20):
        socks[0].sendall(pub_frame(b"tele/t", b"p%%02d" %% i,
                                   qos=(i %% 2), pid=100 + i))
    t0 = time.time()
    while time.time() - t0 < 0.05:
        for kind, conn, payload in host.poll(5):
            if kind == native.EV_TELEMETRY:
                tele_records += 1
                for rec in native.parse_telemetry(payload):
                    if rec[0] == "flight":
                        flights += 1
                    elif rec[0] == "hist":
                        hist_deltas += 1
stop.set(); tog.join()
host.set_telemetry(True, slow_ack_ms=0)
list(host.poll(20))
# protocol error mid-traffic: oversized remaining length tears down the
# conn and dumps its recorder
socks[0].sendall(bytes([0x30, 0xFF, 0xFF, 0xFF, 0x7F]))
deadline = time.time() + 5
closed = False
while not closed and time.time() < deadline:
    for kind, conn, payload in host.poll(20):
        if kind == native.EV_CLOSED and conn == pub_id:
            closed = True
        elif kind == native.EV_TELEMETRY:
            for rec in native.parse_telemetry(payload):
                if rec[0] == "flight":
                    flights += 1
assert closed
assert tele_records > 0 and hist_deltas > 0, (tele_records, hist_deltas)
assert flights > 0, flights
st = host.stats()
assert st["telemetry_batches"] > 0 and st["fr_dumps"] > 0, st
for s in socks:
    try: s.close()
    except OSError: pass
for _ in range(10):
    list(host.poll(10))
host.destroy()
print("SANITIZED-RUN-OK", st["telemetry_batches"], st["fr_dumps"])
"""


# Round-9 cluster-trunk coverage (ISSUE 4): TWO hosts in one process,
# each with its own poll thread, forwarding publishes over a loopback
# trunk link while a control thread races trunk connect/disconnect and
# route add/del ops against both poll threads — the first time two
# native hosts talk to each other, under both sanitizers.
DRIVER_TRUNK = r"""
import socket, struct, sys, threading, time
sys.path.insert(0, %(repo)r)
from emqx_tpu import native

A = native.NativeHost(port=0, max_size=1 << 16)
B = native.NativeHost(port=0, max_size=1 << 16)
tp = B.trunk_listen()

def connect(host, cid):
    s = socket.create_connection(("127.0.0.1", host.port))
    vh = b"\x00\x04MQTT\x04\x02\x00\x3c" + struct.pack(">H", len(cid)) + cid
    s.sendall(bytes([0x10, len(vh)]) + vh)
    return s

def pub_frame(topic, payload, qos=0, pid=0):
    vh = struct.pack(">H", len(topic)) + topic
    if qos:
        vh += struct.pack(">H", pid)
    vh += payload
    return bytes([0x30 | (qos << 1), len(vh)]) + vh

pub_s = connect(A, b"tp")
sub_s = connect(B, b"ts")
ids = {"A": [], "B": []}
framed = {"A": 0, "B": 0}
deadline = time.time() + 15
while ((not ids["A"] or not ids["B"] or framed["A"] < 1 or framed["B"] < 1)
       and time.time() < deadline):
    for name, h in (("A", A), ("B", B)):
        for kind, conn, payload in h.poll(20):
            if kind == native.EV_OPEN:
                ids[name].append(conn)
            elif kind == native.EV_FRAME:
                framed[name] += 1
                h.send(conn, b"\x20\x02\x00\x00")
assert ids["A"] and ids["B"], ids
pa, sb = ids["A"][0], ids["B"][0]
A.enable_fast(pa, 4)
A.permit(pa, "tr/x")
A.trunk_route_add(1, "tr/x")
A.trunk_connect(1, "127.0.0.1", tp)
B.enable_fast(sb, 4)
B.sub_add(sb, "tr/+", qos=1)

stop = threading.Event()
events = {"up": 0, "down": 0}
def poller(h):
    while not stop.is_set():
        for kind, conn, payload in h.poll(20):
            if kind == native.EV_TRUNK and payload:
                if payload[0] == native.TRUNK_UP:
                    events["up"] += 1
                elif payload[0] == native.TRUNK_DOWN:
                    events["down"] += 1
tA = threading.Thread(target=poller, args=(A,))
tB = threading.Thread(target=poller, args=(B,))
tA.start(); tB.start()

def churn():
    j = 0
    while not stop.is_set():
        A.trunk_route_add(1, "churn/%%d" %% (j %% 5))
        A.trunk_route_del(1, "churn/%%d" %% ((j + 2) %% 5))
        A.stats(); B.stats()
        if j %% 60 == 29:
            # teardown/reconnect racing the poll threads (keep state:
            # the replay ring survives and replays on the reconnect)
            A.trunk_disconnect(1, forget=False)
            A.trunk_connect(1, "127.0.0.1", tp)
        j += 1
        time.sleep(0.0005)
ctl = threading.Thread(target=churn)
ctl.start()

def drain():
    sub_s.settimeout(0.2)
    buf = b""
    while not stop.is_set():
        try:
            chunk = sub_s.recv(8192)
        except (TimeoutError, OSError):
            continue
        if not chunk:
            return
        buf += chunk
        # ack any qos1 deliveries so B's ack plane cycles too
        while len(buf) >= 2:
            ln = buf[1]
            if ln & 0x80 or len(buf) < 2 + ln:
                break
            frame, buf = buf[: 2 + ln], buf[2 + ln:]
            if frame[0] >> 4 == 3 and (frame[0] >> 1) & 3 == 1:
                tlen = (frame[2] << 8) | frame[3]
                pid = (frame[4 + tlen] << 8) | frame[5 + tlen]
                try:
                    sub_s.sendall(bytes([0x40, 2, pid >> 8, pid & 0xFF]))
                except OSError:
                    return
dr = threading.Thread(target=drain)
dr.start()

time.sleep(0.3)
N_MSG = 600
for k in range(N_MSG):
    pub_s.sendall(pub_frame(b"tr/x", b"p%%04d" %% k, k & 1,
                            1 + (k %% 100)))
    time.sleep(0.0004)

deadline = time.time() + 20
while time.time() < deadline:
    a, b = A.stats(), B.stats()
    if (a["trunk_out"] > N_MSG // 4 and b["trunk_in"] > 0
            and a["trunk_batches_out"] > 0 and events["up"] > 0):
        break
    time.sleep(0.05)
time.sleep(0.3)
stop.set()
ctl.join(); dr.join(); tA.join(); tB.join()
a, b = A.stats(), B.stats()
assert a["trunk_out"] > 0 and a["trunk_batches_out"] > 0, a
assert b["trunk_in"] > 0 and b["trunk_batches_in"] > 0, b
assert events["up"] > 0, events
for s in (pub_s, sub_s):
    try: s.close()
    except OSError: pass
for _ in range(10):
    list(A.poll(10)); list(B.poll(10))
A.destroy(); B.destroy()
print("SANITIZED-RUN-OK", a["trunk_out"], b["trunk_in"], events)
"""


# Round-10 durable-plane coverage: the poll thread appends batched
# store records (FlushDurables) while foreign threads hammer the SAME
# DurableStore with fetch/consume/gc/stats (the resume-replay and
# marker-consumption call shapes) and race durable route add/del plus
# disable/enable_fast churn (kind-11 handoff emission) against it —
# the store's one-mutex contract under both sanitizers.
DRIVER_DURABLE = r"""
import socket, struct, sys, tempfile, threading, time
sys.path.insert(0, %(repo)r)
from emqx_tpu import native

store = native.NativeStore(tempfile.mkdtemp(), segment_bytes=1 << 16,
                           fsync="batch")
tok = store.register("dur-sess")
host = native.NativeHost(port=0, max_size=1 << 16)
host.attach_store(store)

def mqtt_connect(cid):
    vh = b"\x00\x04MQTT\x04\x02\x00\x3c" + struct.pack(">H", len(cid)) + cid
    return bytes([0x10, len(vh)]) + vh

def mqtt_publish(topic, payload, qos=0, pid=0):
    body = struct.pack(">H", len(topic)) + topic
    if qos:
        body += struct.pack(">H", pid)
    body += payload
    return bytes([0x30 | (qos << 1), len(body)]) + body

socks = [socket.create_connection(("127.0.0.1", host.port))
         for _ in range(2)]
ids = []
for i, s in enumerate(socks):
    s.sendall(mqtt_connect(b"d%%d" %% i))
deadline = time.time() + 15
framed = 0
while (len(ids) < 2 or framed < 2) and time.time() < deadline:
    for kind, conn, payload in host.poll(50):
        if kind == native.EV_OPEN:
            ids.append(conn)
        elif kind == native.EV_FRAME:
            framed += 1
            host.send(conn, b"\x20\x02\x00\x00")
assert len(ids) == 2 and framed == 2, (ids, framed)
sub, pub = ids
for c in ids:
    host.enable_fast(c, 4, 64)
host.sub_add(sub, "du/x", qos=0)
host.durable_add(tok, "du/+", 1)
host.permit(pub, "du/x")

stop = threading.Event()
def store_churn():
    # the resume-replay / consume-on-ack shapes racing the poll
    # thread's batched appends on the store's internal mutex
    j = 0
    while not stop.is_set():
        rows = store.fetch(tok)
        if rows and j %% 3 == 0:
            store.consume(tok, [r[0] for r in rows[: len(rows) // 2 + 1]])
        store.pending(tok)
        store.stats()
        if j %% 40 == 17:
            store.gc()
        j += 1
        time.sleep(0.0005)

def meta_churn():
    # round 18: session-catalog writes, REGISTER retirement, and the
    # trunk-ring journal/ack/fetch surfaces racing the poll thread's
    # FlushDurables + FlushTrunkPeer appends on the same mutex
    j = 0
    while not stop.is_set():
        store.put_session("dur-gc", b'{"subs": {"g/%%d": {}}}' %% j)
        if j %% 7 == 3:
            store.unregister("dur-gc")
        store.sessions()
        store.trunk_put("peerZ", j + 1, b"R" * 48, has_trace=(j & 1) == 1)
        store.trunk_fetch("peerZ")
        if j %% 2:
            store.trunk_ack("peerZ", j + 1)
        store.trunk_pending("peerZ")
        j += 1
        time.sleep(0.0007)

def control_churn():
    # durable route flips + plane demote/promote (handoff emission) +
    # clientid rebinds (conn_cids_) + trunk-ident ring loads
    j = 0
    while not stop.is_set():
        if j %% 10 == 3:
            host.durable_del(tok, "du/+")
            host.durable_add(tok, "du/+", 1)
        if j %% 25 == 7:
            host.disable_fast(pub)
            host.enable_fast(pub, 4, 64, "d1")
            host.permit(pub, "du/x")
        if j %% 33 == 11:
            host.trunk_ident(9, "peerY")
        host.stats()
        j += 1
        time.sleep(0.0008)

th = [threading.Thread(target=store_churn),
      threading.Thread(target=meta_churn),
      threading.Thread(target=control_churn)]
for t in th: t.start()

N_MSG = 400
def blaster():
    for k in range(N_MSG):
        socks[1].sendall(mqtt_publish(b"du/x", b"p%%03d" %% k, k & 1,
                                      1 + (k %% 100)))
        time.sleep(0.0003)
bl = threading.Thread(target=blaster)
bl.start()

durable_events = 0
deadline = time.time() + 25
while time.time() < deadline:
    for kind, conn, payload in host.poll(20):
        if kind == native.EV_DURABLE:
            base, ts, entries = native.parse_durable(payload)
            durable_events += len(entries)
    st = host.stats()
    if (st["durable_in"] > N_MSG // 4 and st["handoffs"] > 0
            and st["store_appends"] > 0):
        break
bl.join()
time.sleep(0.3)
stop.set()
for t in th: t.join()
st = host.stats()
assert st["durable_in"] > 0 and st["store_appends"] > 0, st
assert st["handoffs"] > 0, st
assert durable_events > 0, "no kind-10 records surfaced"
ss = store.stats()
assert ss["appends"] > 0, ss
for s in socks:
    try: s.close()
    except OSError: pass
for _ in range(10):
    list(host.poll(10))
host.destroy()
store.close()
print("SANITIZED-RUN-OK", st["durable_in"], st["handoffs"], ss["appends"])
"""


DRIVER_SN = r"""
import socket, struct, sys, threading, time
sys.path.insert(0, %(repo)r)
from emqx_tpu import native

host = native.NativeHost(port=0, max_size=1 << 16)
sn_port = host.listen_sn("127.0.0.1", 0, gw_id=3)
host.sn_predefined(1, "pre/one")

def sn_connect(cid, clean=True, duration=60):
    body = bytes([0x04, 0x04 if clean else 0x00, 0x01]) + \
        struct.pack(">H", duration) + cid
    return bytes([len(body) + 1]) + body

def sn_subscribe(mid, topic):
    body = bytes([0x12, 0x00]) + struct.pack(">H", mid) + topic
    return bytes([len(body) + 1]) + body

def sn_publish_predef(tid, data, qos=0, mid=0):
    fl = (0x60 if qos == -1 else qos << 5) | 0x01
    body = bytes([0x0C, fl]) + struct.pack(">HH", tid, mid) + data
    return bytes([len(body) + 1]) + body

def sn_register(mid, topic):
    body = bytes([0x0A]) + struct.pack(">HH", 0, mid) + topic
    return bytes([len(body) + 1]) + body

def sn_short(name2, data):
    tid = (name2[0] << 8) | name2[1]
    body = bytes([0x0C, 0x02]) + struct.pack(">HH", tid, 0) + data
    return bytes([len(body) + 1]) + body

PING = bytes([2, 0x16])
DISC = bytes([2, 0x18])

stop = threading.Event()

def retain_churn():
    # retained-snapshot swaps (set/del/expiry-free) + predefined-id
    # flips racing the poll thread's SUBSCRIBE-triggered matching
    j = 0
    while not stop.is_set():
        host.set_retained("r/%%d" %% (j %% 24), b"v%%05d" %% j, j & 1, 0)
        if j %% 5 == 3:
            host.retain_del("r/%%d" %% ((j + 7) %% 24))
        if j %% 17 == 11:
            host.sn_predefined(1, None)
            host.sn_predefined(1, "pre/one")
        host.stats()
        j += 1
        time.sleep(0.0004)

def udp_churn(seed):
    # datagram conn churn: connect (identities recycle so the addr
    # slot sees successor re-CONNECTs), register, subscribe (fires
    # retained delivery), publish qos0/1 via predefined + short ids,
    # ping, sometimes vanish without DISCONNECT
    j = 0
    while not stop.is_set():
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.settimeout(0.05)
        s.connect(("127.0.0.1", sn_port))
        s.send(sn_connect(b"churn-%%d-%%d" %% (seed, j %% 3)))
        s.send(sn_register(1 + (j & 0xFF), b"reg/%%d" %% (j %% 8)))
        s.send(sn_subscribe(2 + (j & 0xFF), b"r/+"))
        s.send(sn_publish_predef(1, b"p%%04d" %% j, qos=j %% 2,
                                 mid=10 + (j & 0xFF)))
        s.send(sn_short(b"ab", b"s%%d" %% j))
        s.send(PING)
        try:
            while True:
                s.recv(4096)
        except OSError:
            pass
        if j %% 3 != 0:
            s.send(DISC)
        s.close()
        j += 1

def qosm1_blaster():
    # publish-without-connect: every datagram rides the shared
    # anonymous conn
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    s.connect(("127.0.0.1", sn_port))
    j = 0
    while not stop.is_set():
        s.send(sn_publish_predef(1, b"m1-%%04d" %% j, qos=-1))
        j += 1
        time.sleep(0.0005)
    s.close()

th = [threading.Thread(target=retain_churn),
      threading.Thread(target=udp_churn, args=(1,)),
      threading.Thread(target=udp_churn, args=(2,)),
      threading.Thread(target=qosm1_blaster)]
for t in th: t.start()

# main thread plays the Python plane exactly like native_server: answer
# CONNECT/SUBSCRIBE punts, fast-enable + permit, fire the retained seam
deadline = time.time() + 25
while time.time() < deadline:
    for kind, conn, payload in host.poll(20):
        if kind != native.EV_FRAME:
            continue
        t = payload[0] >> 4
        if t == 1:                                  # CONNECT
            host.send(conn, b"\x20\x02\x00\x00")
            host.enable_fast(conn, 4, 32)
            host.permit(conn, "pre/one")
        elif t == 8:                                # SUBSCRIBE
            pid = struct.unpack(">H", payload[2:4])[0]
            tl = struct.unpack(">H", payload[4:6])[0]
            filt = payload[6:6 + tl].decode()
            host.sub_add(conn, filt, qos=0)
            host.send(conn, b"\x90\x03" + struct.pack(">H", pid) + b"\x00")
            host.retain_deliver(conn, filt, 1)
        elif t == 3:                                # punted PUBLISH
            qos = (payload[0] >> 1) & 3
            if qos:
                tl = struct.unpack(">H", payload[2:4])[0]
                pid = struct.unpack(">H", payload[4 + tl:6 + tl])[0]
                host.send(conn, b"\x40\x02" + struct.pack(">H", pid))
    st = host.stats()
    if (st["sn_in"] > 150 and st["retain_set"] > 150
            and st["retain_msgs_out"] > 20 and st["sn_qos_m1"] > 50):
        break

stop.set()
for t in th: t.join()
st = host.stats()
assert st["sn_in"] > 0 and st["sn_registers"] > 0, st
assert st["retain_set"] > 0 and st["retain_msgs_out"] > 0, st
assert st["sn_qos_m1"] > 0, st
for _ in range(10):
    list(host.poll(10))
host.destroy()
print("SANITIZED-RUN-OK", st["sn_in"], st["retain_msgs_out"])
"""


DRIVER_SHARDS = r"""
import socket, struct, sys, threading, time
sys.path.insert(0, %(repo)r)
from emqx_tpu import native

# Three shard hosts in one ring group (ISSUE 7): cross-shard publishes
# race set_trace/set_telemetry toggles across ALL poll threads, then
# shard 2 TEARS DOWN mid-traffic — the group-owned doorbells and the
# alive flag are what keep the racing producer memory-safe; afterwards
# the ladder degrades its deliveries ring-full/dead -> punt.
group = native.NativeShardGroup(3)
hosts = [native.NativeHost(port=0, max_size=1 << 16) for _ in range(3)]
for i, h in enumerate(hosts):
    h.join_group(group, i)

def connect(h, cid):
    s = socket.create_connection(("127.0.0.1", h.port))
    vh = b"\x00\x04MQTT\x04\x02\x00\x3c" + struct.pack(">H", len(cid)) + cid
    s.sendall(bytes([0x10, len(vh)]) + vh)
    return s

def pub_frame(topic, payload):
    vh = struct.pack(">H", len(topic)) + topic + payload
    return bytes([0x30, len(vh)]) + vh

pub_s = connect(hosts[0], b"sp")
sub1_s = connect(hosts[1], b"s1")
sub2_s = connect(hosts[2], b"s2")

ids = [[], [], []]
framed = [0, 0, 0]
deadline = time.time() + 15
while ((any(not i for i in ids) or any(f < 1 for f in framed))
       and time.time() < deadline):
    for k in range(3):
        for kind, conn, payload in hosts[k].poll(20):
            if kind == native.EV_OPEN:
                ids[k].append(conn)
            elif kind == native.EV_FRAME:
                framed[k] += 1
                hosts[k].send(conn, b"\x20\x02\x00\x00")
assert all(ids), ids
pub_id, sub1, sub2 = ids[0][0], ids[1][0], ids[2][0]
assert [native.shard_of(c) for c in (pub_id, sub1, sub2)] == [0, 1, 2]
hosts[0].enable_fast(pub_id, 4)
hosts[0].permit(pub_id, "sh/t")
hosts[1].enable_fast(sub1, 4)
hosts[2].enable_fast(sub2, 4)
for h in hosts:                     # the broadcast table discipline
    h.sub_add(sub1, "sh/t", 0, 0)
    h.sub_add(sub2, "sh/t", 0, 0)

stop = threading.Event()
stop2 = threading.Event()           # shard 2 stops early (teardown race)
def poller(k, ev):
    h = hosts[k]
    while not ev.is_set():
        list(h.poll(20))
threads = [threading.Thread(target=poller, args=(k, stop2 if k == 2 else stop))
           for k in range(3)]
for t in threads:
    t.start()

def blaster():
    f = pub_frame(b"sh/t", b"x" * 32) * 16
    while not stop.is_set():
        try:
            pub_s.sendall(f)
        except OSError:
            break
        time.sleep(0.001)
bt = threading.Thread(target=blaster)
bt.start()

def toggler():
    # trace punts + telemetry master switch flipped from a management
    # thread while every shard's poll thread is hot (hosts[2] is left
    # alone: its teardown below must not race a control call)
    j = 0
    while not stop.is_set():
        hosts[0].set_trace(pub_id, j %% 2 == 0)
        hosts[1].set_telemetry(j %% 3 != 0)
        hosts[0].stats(); hosts[1].stats()
        j += 1
        time.sleep(0.001)
tg = threading.Thread(target=toggler)
tg.start()

time.sleep(2.0)
# teardown race: shard 2 dies while shard 0 keeps shipping to it
stop2.set()
threads[2].join()
hosts[2].destroy()
time.sleep(1.0)
st = hosts[0].stats()
assert st["shard_ring_out"] > 0, st
stop.set()
bt.join(); tg.join()
for t in threads[:2]:
    t.join()
st0 = hosts[0].stats()
for s in (pub_s, sub1_s, sub2_s):
    s.close()
hosts[0].destroy(); hosts[1].destroy()
group.destroy()
print("SANITIZED-RUN-OK", st0["shard_ring_out"], st0["shard_ring_full"])
"""


DRIVER_TRACING = r"""
import socket, struct, sys, threading, time
sys.path.insert(0, %(repo)r)
from emqx_tpu import native

# Native distributed tracing (ISSUE 8): set_tracing toggles (enable,
# shift, seed) race SHARDED CROSS-NODE traffic — two shard hosts in a
# ring group blasting cross-shard deliveries while peer 1's OWNER shard
# (1 %% 2 = 1, the round-15 link spread) trunks a remote leg to a third
# (unsharded) host, kind-12 span batches flowing the whole time;
# set_trunk_wire flips race the HELLO negotiation.
group = native.NativeShardGroup(2)
hosts = [native.NativeHost(port=0, max_size=1 << 16) for _ in range(2)]
for i, h in enumerate(hosts):
    h.join_group(group, i)
peer = native.NativeHost(port=0, max_size=1 << 16)
peer_trunk = peer.trunk_listen()

def connect(h, cid):
    s = socket.create_connection(("127.0.0.1", h.port))
    vh = b"\x00\x04MQTT\x04\x02\x00\x3c" + struct.pack(">H", len(cid)) + cid
    s.sendall(bytes([0x10, len(vh)]) + vh)
    return s

def pub_frame(topic, payload):
    vh = struct.pack(">H", len(topic)) + topic + payload
    return bytes([0x30, len(vh)]) + vh

pub_s = connect(hosts[0], b"tp")
sub1_s = connect(hosts[1], b"t1")
subp_s = connect(peer, b"tb")

ids = [[], [], []]
all_hosts = hosts + [peer]
deadline = time.time() + 15
while any(not i for i in ids) and time.time() < deadline:
    for k, h in enumerate(all_hosts):
        for kind, conn, payload in h.poll(20):
            if kind == native.EV_OPEN:
                ids[k].append(conn)
            elif kind == native.EV_FRAME:
                h.send(conn, b"\x20\x02\x00\x00")
assert all(ids), ids
pub_id, sub1, subp = ids[0][0], ids[1][0], ids[2][0]
hosts[0].enable_fast(pub_id, 4)
hosts[0].permit(pub_id, "tr/t")
hosts[1].enable_fast(sub1, 4)
peer.enable_fast(subp, 4)
for h in hosts:                     # broadcast table + remote route
    h.sub_add(sub1, "tr/t", 0, 0)
    h.trunk_route_add(1, "tr/t")
peer.sub_add(subp, "tr/t", 0, 0)
# round 15: peer 1's link lives on its OWNER shard (1 %% 2 = 1); the
# publisher on shard 0 ring-forwards the trunk leg there
hosts[1].trunk_connect(1, "127.0.0.1", peer_trunk)

stop = threading.Event()
def poller(h):
    while not stop.is_set():
        for kind, conn, payload in h.poll(20):
            if kind == native.EV_SPANS:
                native.parse_spans(payload)   # decode under race too
threads = [threading.Thread(target=poller, args=(h,)) for h in all_hosts]
for t in threads:
    t.start()
time.sleep(0.5)
hosts[0].trunk_peer_state(1, True)  # the NON-owner shard's UP mirror

def blaster():
    f = pub_frame(b"tr/t", b"x" * 32) * 16
    while not stop.is_set():
        try:
            pub_s.sendall(f)
        except OSError:
            break
        time.sleep(0.001)
bt = threading.Thread(target=blaster)
bt.start()

def toggler():
    # the tracing control plane flipped from a management thread while
    # every poll thread is hot: enable/shift/seed churn plus trunk wire
    # caps racing the HELLO negotiation on redials
    j = 0
    while not stop.is_set():
        hosts[0].set_tracing(j %% 2 == 0, j %% 7, (1 << 63) | (j << 44))
        hosts[1].set_tracing(j %% 3 != 0, 0, (1 << 62) | (j << 44))
        peer.set_tracing(True, 0, 1 << 61)
        if j %% 5 == 0:
            peer.set_trunk_wire(j %% 2)
        hosts[0].stats(); peer.stats()
        j += 1
        time.sleep(0.001)
tg = threading.Thread(target=toggler)
tg.start()

time.sleep(3.0)
stop.set()
bt.join(); tg.join()
for t in threads:
    t.join()
st0 = hosts[0].stats()
stp = peer.stats()
for s in (pub_s, sub1_s, subp_s):
    s.close()
hosts[0].destroy(); hosts[1].destroy()
group.destroy()
peer.destroy()
assert st0["fast_in"] > 0, st0
assert st0["traced_pubs"] > 0, st0
assert st0["span_batches"] > 0, st0
print("SANITIZED-RUN-OK", st0["traced_pubs"], st0["span_batches"],
      stp["trunk_in"])
"""


# Round-15 faultline coverage: fault arm/disarm churn across EVERY site
# racing the poll threads (arming is all-atomics and explicitly allowed
# from any thread mid-traffic), against a trunk pair + a durable store,
# with blackhole/errno/short modes cycling while qos0/1 traffic flows
# and the sites keep counting — the injector's threading contract under
# both sanitizers.
DRIVER_FAULT = r"""
import socket, struct, sys, threading, time
sys.path.insert(0, %(repo)r)
from emqx_tpu import native

A = native.NativeHost(port=0, max_size=1 << 16)
B = native.NativeHost(port=0, max_size=1 << 16)
store = native.NativeStore("", 1 << 20, "batch")
A.attach_store(store)
tp = B.trunk_listen()
A.set_trunk_ack_timeout(300)

def connect(host, cid):
    s = socket.create_connection(("127.0.0.1", host.port))
    vh = b"\x00\x04MQTT\x04\x02\x00\x3c" + struct.pack(">H", len(cid)) + cid
    s.sendall(bytes([0x10, len(vh)]) + vh)
    return s

def pub_frame(topic, payload, qos=0, pid=0):
    vh = struct.pack(">H", len(topic)) + topic
    if qos:
        vh += struct.pack(">H", pid)
    vh += payload
    return bytes([0x30 | (qos << 1), len(vh)]) + vh

pub_s = connect(A, b"fp")
sub_s = connect(A, b"fs")
ids, framed = [], 0
deadline = time.time() + 15
while (len(ids) < 2 or framed < 2) and time.time() < deadline:
    for kind, conn, payload in A.poll(20):
        if kind == native.EV_OPEN:
            ids.append(conn)
        elif kind == native.EV_FRAME:
            framed += 1
            A.send(conn, b"\x20\x02\x00\x00")
    list(B.poll(0))
assert len(ids) == 2, ids
pub, sub = ids
A.enable_fast(pub, 4)
A.enable_fast(sub, 4)
A.sub_add(sub, "fl/+", qos=1)
A.permit(pub, "fl/x")
A.trunk_route_add(1, "fl/x")
A.trunk_connect(1, "127.0.0.1", tp)

stop = threading.Event()
def poller(h):
    while not stop.is_set():
        list(h.poll(20))
tB = threading.Thread(target=poller, args=(B,))
tB.start()

SITES = list(native.FAULT_SITES)
MODES = ["errno", "short", "blackhole", "full", "skew", "off"]
def churner(salt):
    j = 0
    while not stop.is_set():
        site = SITES[(j + salt) %% len(SITES)]
        mode = MODES[j %% len(MODES)]
        try:
            A.fault_arm(site, mode, n_or_prob=(j %% 3) * 0.25,
                        seed=j + 1, key=0)
        except ValueError:
            pass
        A.fault_fired(site)
        store.fault_arm("store_msync", MODES[(j + 1) %% len(MODES)],
                        n_or_prob=2, seed=j)
        if j %% 7 == 0:
            for s in SITES:
                A.fault_disarm(s)
        j += 1
        time.sleep(0.0005)
c1 = threading.Thread(target=churner, args=(0,))
c2 = threading.Thread(target=churner, args=(5,))
c1.start(); c2.start()

tok = store.register("fl-sid")
def store_hammer():
    k = 0
    while not stop.is_set():
        store.append(1, 1, [tok], "fl/d", b"s%%04d" %% k)
        if k %% 50 == 49:
            store.gc()
        k += 1
        time.sleep(0.0005)
sh = threading.Thread(target=store_hammer)
sh.start()

N_MSG = 1200
sub_s.settimeout(0.01)
for k in range(N_MSG):
    try:
        pub_s.sendall(pub_frame(b"fl/x", b"p%%04d" %% k, k & 1,
                                1 + (k %% 100)))
    except OSError:
        break                      # injected conn fault killed the pub
    for kind, conn, payload in A.poll(0):
        pass
    try:
        while sub_s.recv(8192):
            pass
    except (TimeoutError, OSError):
        pass
    time.sleep(0.0004)

time.sleep(0.2)
stop.set()
c1.join(); c2.join(); sh.join(); tB.join()
for s in SITES:
    A.fault_disarm(s)
a = A.stats()
assert a["fast_in"] > 0 or a["punts"] > 0, a
for _ in range(10):
    list(A.poll(10)); list(B.poll(10))
A.destroy(); B.destroy()
store.close()
print("SANITIZED-RUN-OK", a["faults_injected"])
"""


# Round 16 conn-scale plane: park/inflate churn + connect/teardown
# storms racing the poll thread. One raw host with an aggressive park
# horizon; real socket conns connect, idle into hibernation, and wake
# (first byte / cross-thread send / delivery) while control threads
# churn set_park / set_keepalive / sub_add and a synthetic herd parks
# and re-inflates through deliveries — the wheel (keepalive + park
# timers), the parked-record slab, and the accept governor all run
# under ASan+TSan.
DRIVER_PARK = r"""
import socket, struct, sys, threading, time
sys.path.insert(0, %(repo)r)
from emqx_tpu import native

host = native.NativeHost(port=0, max_size=1 << 16)
host.set_park(True, park_after_ms=60, accept_burst=64)
host.synth_conns(2000, keepalive_ms=600000, sub_every=4,
                 topic_prefix="synth")

def connect(cid):
    s = socket.create_connection(("127.0.0.1", host.port))
    vh = b"\x00\x04MQTT\x04\x02\x00\x3c" + struct.pack(">H", len(cid)) + cid
    s.sendall(bytes([0x10, len(vh)]) + vh)
    return s

stop = threading.Event()
conns = []
lock = threading.Lock()

def churner(salt):
    # connect/teardown storm: half the conns idle long enough to park,
    # then either close or send (a parked first byte -> inflate)
    for round_ in range(8):
        if stop.is_set():
            return
        socks = []
        try:
            socks = [connect(b"pk%%d-%%d" %% (salt, round_ * 8 + i))
                     for i in range(8)]
        except OSError:
            pass
        time.sleep(0.12)  # beyond the park horizon
        for j, s in enumerate(socks):
            try:
                if j %% 2:
                    s.sendall(b"\xc0\x00")   # parked ping fast path
                    time.sleep(0.005)
                s.close()
            except OSError:
                pass

def controller():
    # control ops racing the poll thread: keepalive re-arms, park
    # toggles, table churn, cross-thread sends to (parked) conns
    n = 0
    while not stop.is_set():
        n += 1
        with lock:
            targets = list(conns)[-16:]
        for c in targets:
            host.set_keepalive(c, 5000 + (n %% 7) * 1000)
            host.send(c, b"\xd0\x00")
        host.set_park(True, park_after_ms=60 + (n %% 3) * 20,
                      accept_burst=64)
        host.sub_add((n %% 8) + 1, "churn/%%d" %% (n %% 32), qos=1)
        host.sub_del((n %% 8) + 1, "churn/%%d" %% ((n + 16) %% 32))
        time.sleep(0.01)

threads = [threading.Thread(target=churner, args=(i,)) for i in range(3)]
threads.append(threading.Thread(target=controller))
for t in threads: t.start()

deadline = time.time() + 9
parked_seen = 0
while time.time() < deadline:
    for kind, cid, payload in host.poll(20):
        if kind == native.EV_OPEN:
            with lock:
                conns.append(cid)
            host.send(cid, b"\x20\x02\x00\x00")
            host.enable_fast(cid, 4)
    st = host.stats()
    parked_seen = max(parked_seen, st["conns_parked"])
stop.set()
for t in threads: t.join()
for _ in range(10):
    list(host.poll(10))
cc = host.conn_counts()
st = host.stats()
assert parked_seen > 0, "nothing ever parked"
assert st["conns_inflated"] > 0, "nothing ever inflated"
host.destroy()
print("SANITIZED-RUN-OK", parked_seen, st["conns_inflated"],
      st["parked_pings"])
"""


DRIVER_COAP = r"""
import socket, sys, threading, time
sys.path.insert(0, %(repo)r)
from emqx_tpu import native
from emqx_tpu.gateway import coap as C

host = native.NativeHost(port=0, max_size=1 << 16)
coap_port = host.listen_coap("127.0.0.1", 0)
host.set_coap_ack_timeout(50)
f = C.Frame()

def req(code, segs, mid, token=b"t", obs=None, queries=(), payload=b"",
        con=True, extra=()):
    opts = [(C.OPT_URI_PATH, s) for s in segs] + list(extra)
    if obs is not None:
        opts.append((C.OPT_OBSERVE, obs))
    for q in queries:
        opts.append((C.OPT_URI_QUERY, q))
    return f.serialize(C.CoapMessage(C.CON if con else C.NON, code, mid,
                                     token, opts, payload))

stop = threading.Event()

def control_churn():
    # retained-mirror swaps + the plain-GET completeness gate + the
    # CON backoff knob flipping, all racing the poll thread's dispatch
    j = 0
    while not stop.is_set():
        host.set_retained("cr/" + str(j %% 16), b"v" + str(j).encode(),
                          j & 1, 0)
        if j %% 7 == 3:
            host.retain_del("cr/" + str((j + 5) %% 16))
        if j %% 11 == 5:
            host.coap_retain_state(j %% 2 == 0)
        if j %% 13 == 7:
            host.set_coap_ack_timeout(50 + (j %% 3) * 25)
        host.stats()
        j += 1
        time.sleep(0.0004)

def udp_churn(seed):
    # endpoint churn: observe register (CON), NON + CON-qos1 publishes
    # (with one byte-identical dup for the MID-dedup window), CoAP
    # pings, a block-wise punt, plain GETs against the flipping
    # retained gate, CON-notify ACK/RST answers, a new-identity
    # re-register, and endpoints that vanish mid-rexmit
    j = 0
    while not stop.is_set():
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.settimeout(0.05)
        s.connect(("127.0.0.1", coap_port))
        cid = ("clientid=ch-" + str(seed) + "-" + str(j %% 3)).encode()
        t = str(j %% 16).encode()
        s.send(req(C.GET, [b"ps", b"cr", t], 1, token=b"ob", obs=b"",
                   queries=[cid, b"qos=1"]))
        s.send(req(C.POST, [b"ps", b"cr", t], 2, token=b"p1",
                   queries=[cid], payload=b"x", con=False))
        dup = req(C.POST, [b"ps", b"cr", t], 3, token=b"p2",
                  queries=[cid, b"qos=1"], payload=b"y")
        s.send(dup)
        s.send(dup)
        s.send(f.serialize(C.CoapMessage(C.CON, C.EMPTY, 9, b"")))
        s.send(req(C.POST, [b"ps", b"blk"], 4, token=b"bk",
                   queries=[cid], payload=b"c",
                   extra=[(C.OPT_BLOCK1, b"\x08")]))
        s.send(req(C.GET, [b"ps", b"cr", t], 5, token=b"rd",
                   queries=[cid]))
        try:
            while True:
                for m in f.parse(s.recv(4096), None)[0]:
                    if m.type == C.CON:
                        t2 = C.ACK if (j + m.mid) %% 3 else C.RST
                        s.send(f.serialize(
                            C.CoapMessage(t2, C.EMPTY, m.mid, b"")))
        except OSError:
            pass
        if j %% 2:
            s.send(req(C.POST, [b"ps", b"cr", b"0"], 6,
                       queries=[b"clientid=re-" + str(seed).encode()],
                       payload=b"z", con=False))
        s.close()
        j += 1

th = [threading.Thread(target=control_churn),
      threading.Thread(target=udp_churn, args=(1,)),
      threading.Thread(target=udp_churn, args=(2,))]
for t in th: t.start()

# main thread plays the Python plane exactly like native_server: answer
# CONNECT/SUBSCRIBE/UNSUBSCRIBE/qos1 punts, fast-enable + permit, and
# serve kind-13 oracle punts with a canned response
import struct
deadline = time.time() + 25
while time.time() < deadline:
    for kind, conn, payload in host.poll(20):
        if kind == 13:
            try:
                m = f.parse(payload, None)[0][0]
            except Exception:
                continue
            if m.type in (0, 1) and m.code:
                host.coap_send(conn, f.serialize(C.CoapMessage(
                    C.ACK if m.type == 0 else C.NON, C.NOT_FOUND,
                    m.mid, m.token)))
            continue
        if kind != native.EV_FRAME:
            continue
        t = payload[0] >> 4
        if t == 1:                                  # CONNECT
            host.send(conn, b"\x20\x02\x00\x00")
            host.enable_fast(conn, 4, 32)
            for k in range(16):
                host.permit(conn, "cr/" + str(k))
        elif t == 8:                                # SUBSCRIBE
            pid = struct.unpack(">H", payload[2:4])[0]
            tl = struct.unpack(">H", payload[4:6])[0]
            filt = payload[6:6 + tl].decode()
            host.sub_add(conn, filt, qos=1)
            host.send(conn, b"\x90\x03" + struct.pack(">H", pid) + b"\x01")
        elif t == 10:                               # UNSUBSCRIBE
            pid = struct.unpack(">H", payload[2:4])[0]
            tl = struct.unpack(">H", payload[4:6])[0]
            host.sub_del(conn, payload[6:6 + tl].decode())
            host.send(conn, b"\xB0\x02" + struct.pack(">H", pid))
        elif t == 3:                                # punted PUBLISH
            qos = (payload[0] >> 1) & 3
            if qos:
                tl = struct.unpack(">H", payload[2:4])[0]
                pid = struct.unpack(">H", payload[4 + tl:6 + tl])[0]
                host.send(conn, b"\x40\x02" + struct.pack(">H", pid))
    st = host.stats()
    if (st["coap_in"] > 150 and st["coap_notifies"] > 20
            and st["coap_punts"] > 10 and st["coap_pings"] > 20):
        break

stop.set()
for t in th: t.join()
st = host.stats()
assert st["coap_in"] > 0 and st["coap_notifies"] > 0, st
assert st["coap_punts"] > 0 and st["coap_pings"] > 0, st
assert st["coap_dedup_hits"] > 0, st
for _ in range(10):
    list(host.poll(10))
host.destroy()
print("SANITIZED-RUN-OK", st["coap_in"], st["coap_notifies"],
      st["coap_giveups"])
"""


@pytest.mark.parametrize("sanitizer", ["address", "thread"])
@pytest.mark.parametrize("driver", ["host", "fastpath", "lane", "ws",
                                    "telemetry", "trunk", "durable", "sn",
                                    "shards", "tracing", "fault", "park",
                                    "coap"])
def test_host_cc_sanitized(sanitizer, driver, tmp_path):
    if sanitizer not in _SAN_LIBS:
        pytest.skip(f"{sanitizer} sanitizer runtime not available")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {
        **os.environ,
        "EMQX_NATIVE_SANITIZE": sanitizer,
        "LD_PRELOAD": _SAN_LIBS[sanitizer],
        "ASAN_OPTIONS": "halt_on_error=1:detect_leaks=0",
        # leak detection off: the PYTHON interpreter under LD_PRELOAD
        # reports its own arena allocs; host.cc still gets full
        # use-after-free/overflow/race coverage
        "TSAN_OPTIONS": "halt_on_error=1:report_signal_unsafe=0",
    }
    src = {"host": DRIVER, "fastpath": DRIVER_FASTPATH,
           "lane": DRIVER_LANE, "ws": DRIVER_WS,
           "telemetry": DRIVER_TELEMETRY, "trunk": DRIVER_TRUNK,
           "durable": DRIVER_DURABLE, "sn": DRIVER_SN,
           "shards": DRIVER_SHARDS, "tracing": DRIVER_TRACING,
           "fault": DRIVER_FAULT, "park": DRIVER_PARK,
           "coap": DRIVER_COAP}[driver]
    proc = subprocess.run(
        [sys.executable, "-c", src % {"repo": repo}],
        capture_output=True, text=True, env=env, timeout=180)
    assert "SANITIZED-RUN-OK" in proc.stdout, (
        f"rc={proc.returncode}\nstdout={proc.stdout[-2000:]}\n"
        f"stderr={proc.stderr[-4000:]}")
    for marker in ("ERROR: AddressSanitizer", "WARNING: ThreadSanitizer",
                   "ERROR: ThreadSanitizer"):
        assert marker not in proc.stderr, proc.stderr[-4000:]
