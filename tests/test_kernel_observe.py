"""Kernel-plane observability (ISSUE 19): in-kernel per-batch counters
ride the existing collect device_get, the DeviceMetricsFold turns them
plus the submit/step/decode wall timings into histograms, trie-health
gauges, ledger reasons and stitched spans — the same surfaces every
other plane exports through."""

import numpy as np
import pytest

from emqx_tpu.broker.broker import Broker
from emqx_tpu.core.message import Message
from emqx_tpu.models.router_model import RouterModel
from emqx_tpu.observe.device_metrics import (KERNEL_COUNTER_FIELDS,
                                             DeviceMetricsFold)
from emqx_tpu.observe.metrics import Metrics, DegradationLedger
from emqx_tpu.observe.trace import SpanCollector
from emqx_tpu.router.index import ShardedTrieIndex, TrieIndex

# a full exact/plus fan: the frontier doubles every level, so K=2
# overflows on the 4-deep topic (differentially verified in
# test_kernel_counters_lint)
FAN_FILTERS = ["a/b/c/d", "a/b/c/+", "a/b/+/d", "a/b/+/+",
               "a/+/c/d", "a/+/c/+", "a/+/+/d", "a/+/+/+"]


def _fold(model, **kw):
    metrics = Metrics()
    ledger = DegradationLedger(metrics)
    fold = DeviceMetricsFold(metrics, ledger=ledger,
                             spans=SpanCollector(), model=model,
                             node="n1", **kw)
    model.telemetry = fold
    return metrics, ledger, fold


def _drive(model, topics):
    return model.publish_batch_collect(model.publish_batch_submit(topics))


# -- fold math ---------------------------------------------------------------


def test_fold_counters_and_stage_hists_flat():
    model = RouterModel(TrieIndex(max_levels=8), n_sub_slots=64)
    for f in ("a/+/c", "a/b/#", "d/e"):
        model.subscribe(f, 1)
    metrics, _ledger, fold = _fold(model)

    _drive(model, ["a/b/c", "d/e", "x/y"])
    assert fold.batches == 1
    assert fold.last is not None and fold.last.n_shards == 1
    last = fold.last.as_dict()
    assert last["cand_pre"] == 3          # a/+/c + a/b/# + d/e
    assert last["cand_post"] == 3
    assert last["overflow_rows"] == 0 and last["trunc_rows"] == 0
    assert last["frontier_peak"] >= 2 and last["probe_iters"] >= 1
    # every stage histogram observed exactly one batch
    for h in fold.stage_hists().values():
        assert h.count == 1
    _drive(model, ["a/b/c"])
    assert fold.batches == 2
    assert fold.totals["cand_pre"] == 3 + 2   # sums across batches

    ks = fold.kernel_summary()
    assert ks["batches"] == 2
    assert set(ks["counters"]) == set(KERNEL_COUNTER_FIELDS)
    assert ks["stages"]["step"]["count"] == 2


def test_fold_truncation_counter():
    # M=1 candidate cap: a topic matching 2 filters truncates its row
    model = RouterModel(TrieIndex(max_levels=8), n_sub_slots=64, M=1)
    model.subscribe("t/+", 1)
    model.subscribe("t/1", 2)
    _metrics, _ledger, fold = _fold(model)
    _drive(model, ["t/1"])
    last = fold.last.as_dict()
    assert last["trunc_rows"] >= 1
    assert last["cand_post"] <= last["cand_pre"]


def test_fold_gauges_flat_and_upload_slots():
    model = RouterModel(TrieIndex(max_levels=8), n_sub_slots=64)
    for f in ("a/b", "c/+", "d/#"):
        model.subscribe(f, 1)
    metrics, _ledger, fold = _fold(model)
    model.refresh()
    g = fold.gauges()
    assert g["filters_total"] == 3 and g["shards"] == 1
    assert g["shard_skew"] == 1.0
    assert 0 < g["node_occupancy"] < 1 and 0 < g["edge_load"] < 1
    assert g["uploads"] >= 1
    # the promoted fixed slots sync from the model's ad-hoc counters
    assert metrics.val("kernel.uploads") == model.upload_count >= 1
    # an incremental subscribe after the first upload scatter-patches:
    # patch slot + unpadded byte gauge move
    model.subscribe("new/filter/x", 3)
    model.refresh()
    g = fold.gauges()
    assert g["upload_patches"] == model.patch_count >= 1
    assert g["patch_upload_bytes"] > 0
    assert metrics.val("kernel.upload_patches") >= 1


def test_fold_gauges_sharded_skew():
    idx = ShardedTrieIndex(4, max_levels=8)
    model = RouterModel(idx, n_sub_slots=64)
    # all filters hash wherever they hash; skew = max/mean over the
    # per-shard live-filter counts, computed from the index itself
    for i in range(16):
        model.subscribe(f"s/{i}/x", 1)
    _metrics, _ledger, fold = _fold(model)
    g = fold.gauges()
    assert g["shards"] == 4
    assert isinstance(g["filters"], list) and len(g["filters"]) == 4
    assert sum(g["filters"]) == g["filters_total"] == 16
    counts = [c for c in g["filters"] if c > 0]
    assert g["shard_skew"] == pytest.approx(max(g["filters"]) / 4.0)
    assert isinstance(g["node_occupancy"], list)
    # per-shard counters decode as [S, C]
    _drive(model, ["s/3/x", "s/7/x"])
    assert fold.last.n_shards == 4
    assert int(fold.last.field("cand_pre").sum()) == 2


# -- trace stitching ---------------------------------------------------------


def test_span_stitching_submit_collect():
    model = RouterModel(TrieIndex(max_levels=8), n_sub_slots=64)
    model.subscribe("a/b", 1)
    _metrics, _ledger, fold = _fold(model)
    _drive(model, ["a/b"])
    assert fold.last_trace_id != 0
    stages = fold.spans.stages(fold.last_trace_id)
    assert stages == ["kernel_submit", "kernel_collect"]
    spans = fold.spans.trace(fold.last_trace_id)
    assert spans[0][0] <= spans[1][0]        # monotone timeline
    # JSON shape matches the native server's spans_recent
    rec = fold.spans_recent(4)
    assert rec and rec[0]["trace_id"] == f"{fold.last_trace_id:016x}"
    assert [s["stage"] for s in rec[0]["spans"]] == stages
    assert rec[0]["spans"][0]["node"] == "n1"
    # the sampled batch hung an exemplar on the step histogram
    assert fold.stage_hists()["step"].exemplars


def test_span_sampling_1_in_n():
    model = RouterModel(TrieIndex(max_levels=8), n_sub_slots=64)
    model.subscribe("a/b", 1)
    _metrics, _ledger, fold = _fold(model, sample_every=4)
    for _ in range(8):
        _drive(model, ["a/b"])
    assert len(fold.spans) == 2              # batches 1 and 5


# -- broker fallback seam ----------------------------------------------------


def test_broker_kernel_overflow_ledger():
    model = RouterModel(TrieIndex(max_levels=8), n_sub_slots=64, K=2)
    metrics = Metrics()
    b = Broker(router_model=model, metrics=metrics)
    b.ledger = DegradationLedger(metrics)
    for i, f in enumerate(FAN_FILTERS):
        b.subscribe(f"c{i}", f)
    out = b.publish_batch([Message(topic="a/b/c/d"),
                           Message(topic="a/b/c/x")])
    # the K=2 frontier punts both rows to the host oracle — delivery
    # still complete...
    assert len(out[0]) == len(FAN_FILTERS)
    # ...and the degradation is on the ledger with its row count
    assert b.ledger.totals()["kernel_overflow"] == 2
    ev = [e for e in b.ledger.recent(8)
          if e["reason"] == "kernel_overflow"]
    assert ev and ev[-1]["count"] == 2
    assert metrics.val("messages.ledger.kernel_overflow") == 2
    assert metrics.val("messages.ledger.kernel_hostmatch") == 0


def test_broker_kernel_hostmatch_ledger(monkeypatch):
    monkeypatch.setenv("EMQX_TPU_CPU_KERNEL", "host")
    model = RouterModel(TrieIndex(max_levels=8), n_sub_slots=64)
    assert model._host_matcher is not None
    metrics = Metrics()
    b = Broker(router_model=model, metrics=metrics)
    b.ledger = DegradationLedger(metrics)
    b.subscribe("c1", "a/b")
    out = b.publish_batch([Message(topic="a/b")])
    assert "c1" in out[0]
    assert metrics.val("messages.kernel.hostmatch") == 1
    assert b.ledger.totals()["kernel_hostmatch"] == 1
    assert metrics.val("messages.ledger.kernel_hostmatch") == 1
    assert b.ledger.totals().get("kernel_overflow", 0) == 0


# -- escape hatch ------------------------------------------------------------


def test_kernel_telemetry_escape_hatch(monkeypatch):
    monkeypatch.setenv("EMQX_TPU_KERNEL_TELEMETRY", "0")
    model = RouterModel(TrieIndex(max_levels=8), n_sub_slots=64)
    assert model.kernel_telemetry is False
    model.subscribe("a/b", 1)
    _metrics, _ledger, fold = _fold(model)
    _drive(model, ["a/b"])
    # stage timings still fold (host-side clocks cost nothing); the
    # device counters are compiled out
    assert fold.batches == 1
    assert fold.last is None
    monkeypatch.setenv("EMQX_TPU_KERNEL_TELEMETRY", "1")
    assert RouterModel(TrieIndex()).kernel_telemetry is True
    # explicit ctor flag beats the env
    monkeypatch.setenv("EMQX_TPU_KERNEL_TELEMETRY", "0")
    assert RouterModel(TrieIndex(),
                       kernel_telemetry=True).kernel_telemetry is True


# -- app wiring: prometheus, $SYS, mgmt, server surface ----------------------


def _app():
    from emqx_tpu.app import BrokerApp

    model = RouterModel(TrieIndex(max_levels=8), n_sub_slots=64)
    return BrokerApp(router_model=model)


def test_app_wires_fold_and_prometheus_gauges():
    app = _app()
    assert app.device_metrics is not None
    assert app.broker.model.telemetry is app.device_metrics
    # the kernel fold serves tracing spans until a native server boots
    assert app.native_spans_fn == app.device_metrics.spans_recent
    app.broker.subscribe("c1", "a/b")
    app.broker.publish_batch([Message(topic="a/b")])
    out = app.prometheus()
    assert "emqx_kernel_filters_total" in out
    assert "emqx_kernel_shard_skew" in out
    assert "emqx_kernel_batches" in out
    assert "emqx_latency_kernel_submit_seconds_count" in out
    assert "emqx_latency_kernel_decode_seconds_count" in out
    ks = app.kernel_summary()
    assert ks["batches"] == 1 and "gauges" in ks


def test_app_without_kernel_telemetry(monkeypatch):
    monkeypatch.setenv("EMQX_TPU_KERNEL_TELEMETRY", "off")
    app = _app()
    assert app.device_metrics is None
    assert app.kernel_summary() == {}
    assert "emqx_kernel_batches" not in app.prometheus()


def test_sys_kernel_heartbeat_renders_at_zero():
    from emqx_tpu.observe.sys import SysHeartbeat

    app = _app()
    seen = {}
    hb = SysHeartbeat("n1", lambda m: seen.__setitem__(
        m.topic, m.payload), metrics=app.metrics,
        kernel=app.device_metrics)
    hb.publish_kernel()
    for stage in ("submit", "step", "decode"):
        assert seen[f"$SYS/brokers/n1/kernel/{stage}/p50"] == b"0.000"
        assert seen[f"$SYS/brokers/n1/kernel/{stage}/p99"] == b"0.000"
        assert seen[f"$SYS/brokers/n1/kernel/{stage}/count"] == b"0"
    # and it rides the slow tick next to metrics/latency/ledger
    hb.tick(now=1e12)
    assert "$SYS/brokers/n1/kernel/step/p99" in seen


def test_mgmt_kernel_stats_endpoint():
    from emqx_tpu.mgmt.api import ApiError, ManagementApi

    app = _app()
    app.broker.subscribe("c1", "a/b")
    app.broker.publish_batch([Message(topic="a/b")])
    api = ManagementApi(app)
    snap = api.h_kernel_stats({}, None)
    assert snap["gauges"]["filters_total"] == 1
    assert snap["summary"]["batches"] == 1
    assert snap["last_per_shard"]["cand_pre"] == [1]
    status, body = api.handle("GET", "/api/v5/kernel/stats", {}, None,
                              authed=True)
    assert status == 200 and body["summary"]["batches"] == 1

    app.device_metrics = None
    with pytest.raises(ApiError) as ei:
        api.h_kernel_stats({}, None)
    assert ei.value.status == 404


def test_server_kernel_summary_surface():
    from emqx_tpu.broker.server import BrokerServer

    app = _app()
    srv = BrokerServer(app=app, port=0)
    app.broker.subscribe("c1", "a/b")
    app.broker.publish_batch([Message(topic="a/b")])
    ks = srv.kernel_summary()
    assert ks["batches"] == 1
    assert ks["stages"]["submit"]["count"] == 1
