"""Trie oracle tests — mirrors apps/emqx/test/emqx_trie_SUITE.erl and the
inline eunit block in emqx_trie.erl:356-420."""

import random

from emqx_tpu.core import topic as T
from emqx_tpu.router.trie import Trie
from emqx_tpu.router.router import Router


def test_insert_match_basic():
    t = Trie()
    for f in ["a/+/c", "a/#", "+/b/c", "#", "a/b/+"]:
        t.insert(f)
    assert sorted(t.match("a/b/c")) == sorted(["a/+/c", "a/#", "+/b/c", "#", "a/b/+"])
    assert sorted(t.match("a")) == sorted(["a/#", "#"])
    assert sorted(t.match("x/y")) == ["#"]
    assert t.match("$SYS/x") == []


def test_refcounts():
    t = Trie()
    assert t.insert("a/+") is True
    assert t.insert("a/+") is False     # second ref
    assert t.delete("a/+") is False     # still one ref left
    assert t.match("a/b") == ["a/+"]
    assert t.delete("a/+") is True
    assert t.match("a/b") == []
    assert t.is_empty()


def test_delete_prunes_but_keeps_shared_prefix():
    t = Trie()
    t.insert("a/b/+")
    t.insert("a/b/#")
    t.delete("a/b/+")
    assert t.match("a/b/c") == ["a/b/#"]
    t.delete("a/b/#")
    assert t.is_empty()


def test_delete_nonexistent():
    t = Trie()
    t.insert("a/+")
    assert t.delete("a/#") is False
    assert t.delete("x/+") is False
    assert t.match("a/z") == ["a/+"]


def test_match_randomized_vs_linear_scan(rng):
    alphabet = ["a", "b", "c", "d", ""]
    filters = set()
    t = Trie()
    for _ in range(400):
        ws = [rng.choice(alphabet + ["+", "#"]) for _ in range(rng.randint(1, 6))]
        if "#" in ws:
            ws = ws[: ws.index("#") + 1]
        f = T.join(ws)
        if not T.wildcard(ws):
            ws[rng.randrange(len(ws))] = "+"
            f = T.join(ws)
        if T.validate_filter(f):
            filters.add(f)
            t.insert(f)
    for _ in range(2000):
        nw = [rng.choice(["a", "b", "c", "d", "$x"]) for _ in range(rng.randint(1, 6))]
        name = T.join(nw)
        expect = sorted(f for f in filters if T.match(name, f))
        got = sorted(t.match(name))
        assert got == expect, (name, got, expect)


def test_churn_refcount_consistency(rng):
    """Random insert/delete interleavings keep match == linear scan."""
    t = Trie()
    counts: dict[str, int] = {}
    pool = ["a/+", "a/#", "+/+", "a/b/+", "+/b/#", "#", "+"]
    for _ in range(3000):
        f = rng.choice(pool)
        if rng.random() < 0.55:
            t.insert(f)
            counts[f] = counts.get(f, 0) + 1
        else:
            expect_gone = counts.get(f, 0) == 1
            got = t.delete(f)
            if counts.get(f, 0) > 0:
                assert got is expect_gone
                counts[f] -= 1
    live = sorted(f for f, c in counts.items() if c > 0)
    assert sorted(f for f, _ in t.filters()) == live


def test_router_match_routes():
    r = Router()
    r.add_route("a/b/c", "node1")
    r.add_route("a/+/c", "node2")
    r.add_route("a/#", "node1")
    r.add_route("x/y", "node3")
    got = {(rt.topic, rt.dest) for rt in r.match_routes("a/b/c")}
    assert got == {("a/b/c", "node1"), ("a/+/c", "node2"), ("a/#", "node1")}
    assert r.stats() == {"routes.count": 4, "topics.count": 4, "filters.count": 2}


def test_router_multi_dest_and_cleanup():
    r = Router()
    r.add_route("t/+", "n1")
    r.add_route("t/+", "n2")
    assert len(r.match_routes("t/x")) == 2
    # trie holds one filter entry per distinct dest insert (refcounted)
    r.delete_route("t/+", "n1")
    assert [rt.dest for rt in r.match_routes("t/x")] == ["n2"]
    r.add_route("u/#", "n2")
    assert r.cleanup_dest("n2") == 2
    assert r.match_routes("t/x") == []
    assert r.stats()["filters.count"] == 0


def test_router_delta_log():
    r = Router()
    r.add_route("a/+", "n1")
    r.add_route("a/+", "n2")
    r.delete_route("a/+", "n1")
    deltas = r.deltas_since(0)
    assert [(d.op, d.dest, d.filter_new) for d in deltas] == [
        ("add", "n1", True),
        ("add", "n2", False),
        ("del", "n1", False),
    ]
    assert r.deltas_since(r.seq) == []


def test_deep_filter_no_recursion_limit():
    t = Trie()
    deep = "/".join(["a"] * 3000) + "/#"
    t.insert(deep)
    assert t.match("/".join(["a"] * 3500)) == [deep]


def test_delta_log_trim():
    r = Router()
    for i in range(5):
        r.add_route(f"t/{i}/+", "n")
    r.trim_log(3)
    assert r.deltas_since(2) is None          # trimmed → full resync
    assert [d.seq for d in r.deltas_since(3)] == [4, 5]
    r.trim_log(100)                            # clamped to current seq
    assert r.deltas_since(5) == []
    assert len(r.snapshot_filters()) == 5
