"""Observability tests: metrics counters, metrics-worker rates, stats
gauges/updaters, alarms, $SYS heartbeats, prometheus rendering, and
live-broker counter integration."""

import asyncio

from emqx_tpu.observe import prometheus
from emqx_tpu.observe.alarm import AlarmManager
from emqx_tpu.observe.metrics import Metrics, MetricsWorker
from emqx_tpu.observe.stats import Stats


def test_metrics_fixed_and_dynamic():
    m = Metrics()
    m.inc("messages.publish")
    m.inc("messages.publish", 4)
    assert m.val("messages.publish") == 5
    m.inc("rules.my_rule.matched")          # dynamic spillover
    assert m.val("rules.my_rule.matched") == 1
    assert m.all()["messages.publish"] == 5
    m.reset()
    assert m.val("messages.publish") == 0
    assert m.val("rules.my_rule.matched") == 0


def test_metrics_packet_helpers():
    m = Metrics()
    m.inc_recv_packet("connect")
    m.inc_sent_packet("connack")
    m.inc_msg("received", 1)
    assert m.val("packets.received") == 1
    assert m.val("packets.connect.received") == 1
    assert m.val("packets.connack.sent") == 1
    assert m.val("messages.qos1.received") == 1


def test_metrics_worker_counters_and_rates():
    w = MetricsWorker()
    w.create_metrics("rule:1", ["matched", "failed"])
    for _ in range(10):
        w.inc("rule:1", "matched")
    assert w.get("rule:1", "matched") == 10
    assert w.get_counters("rule:1") == {"matched": 10, "failed": 0}
    t = 100.0
    w.tick(t)
    for _ in range(50):
        w.inc("rule:1", "matched")
    w.tick(t + 5.0)                          # 10/s instantaneous
    assert w.get_rate("rule:1", "matched") > 3.0
    w.clear_metrics("rule:1")
    assert w.get("rule:1", "matched") == 0


def test_stats_setstat_and_watermark():
    s = Stats()
    s.setstat("connections.count", 5, "connections.max")
    s.setstat("connections.count", 3, "connections.max")
    assert s.getstat("connections.count") == 3
    assert s.getstat("connections.max") == 5


def test_stats_updaters():
    s = Stats()
    n = {"v": 7}
    s.set_updater("topics.count", lambda: n["v"], "topics.max")
    s.tick()
    assert s.getstat("topics.count") == 7
    n["v"] = 3
    s.tick()
    assert s.getstat("topics.count") == 3
    assert s.getstat("topics.max") == 7


def test_alarm_lifecycle_and_history():
    events = []
    a = AlarmManager(history_size=2,
                     on_change=lambda ev, al: events.append((ev, al.name)))
    assert a.activate("high_cpu", {"usage": 99}, "cpu high")
    assert not a.activate("high_cpu")        # already active
    assert a.is_active("high_cpu")
    assert a.deactivate("high_cpu")
    assert not a.deactivate("high_cpu")
    assert [e[0] for e in events] == ["activated", "deactivated"]
    for i in range(4):
        a.activate(f"al{i}")
        a.deactivate(f"al{i}")
    assert len(a.get_alarms("deactivated")) == 2       # bounded history
    a.ensure("mem", True)
    a.ensure("mem", True)                    # idempotent
    assert len(a.get_alarms("activated")) == 1
    a.delete_all_deactivated()
    assert a.get_alarms("deactivated") == []


def test_sys_heartbeat_publishes_retained():
    from emqx_tpu.observe.sys import SysHeartbeat

    msgs = []
    sys_hb = SysHeartbeat("n1", msgs.append, heartbeat_s=30)
    sys_hb.heartbeat()
    topics = [m.topic for m in msgs]
    assert "$SYS/brokers" in topics
    assert "$SYS/brokers/n1/version" in topics
    assert "$SYS/brokers/n1/uptime" in topics
    assert all(m.retain for m in msgs)
    # tick twice in the same window: only one heartbeat
    msgs.clear()
    sys_hb.tick(1000.0)
    sys_hb.tick(1001.0)
    assert len([m for m in msgs if m.topic.endswith("version")]) == 1


def test_prometheus_render():
    m = Metrics()
    m.inc("messages.publish", 42)
    s = Stats()
    s.setstat("connections.count", 3)
    text = prometheus.render(m, s, node="n1")
    assert 'emqx_messages_publish{node="n1"} 42' in text
    assert 'emqx_connections_count{node="n1"} 3' in text
    assert "# TYPE emqx_messages_publish counter" in text
    assert "# TYPE emqx_connections_count gauge" in text


def test_live_broker_metrics_and_stats():
    from emqx_tpu.app import BrokerApp
    from emqx_tpu.broker.server import BrokerServer
    from emqx_tpu.mqtt.client import MqttClient

    async def main():
        app = BrokerApp()
        srv = BrokerServer(app=app, port=0)
        await srv.start()
        try:
            c = MqttClient(port=srv.port, clientid="obs1")
            await c.connect()
            await c.subscribe("t/#", qos=1)
            await c.publish("t/1", b"x", qos=1)
            await c.recv()
            m = app.metrics
            assert m.val("packets.connect.received") == 1
            assert m.val("packets.connack.sent") == 1
            assert m.val("packets.publish.received") == 1
            assert m.val("packets.publish.sent") >= 1
            assert m.val("messages.qos1.received") == 1
            assert m.val("client.connected") == 1
            assert m.val("bytes.received") > 0
            app.stats.tick()
            assert app.stats.getstat("connections.count") == 1
            assert app.stats.getstat("subscriptions.count") == 1
            text = app.prometheus()
            assert "emqx_packets_connect_received" in text
            await c.disconnect()
            await c.close()
            await asyncio.sleep(0.05)
            assert m.val("client.disconnected") == 1
        finally:
            await srv.stop()

    asyncio.run(main())


def test_sys_messages_reach_subscribers_via_broker():
    from emqx_tpu.app import BrokerApp
    from emqx_tpu.broker.channel import Channel
    from emqx_tpu.mqtt import packet as P

    app = BrokerApp()
    ch = Channel(app.broker, app.cm)
    ch.handle_in(P.Connect(proto_ver=P.MQTT_V5, clientid="sysw"))
    ch.handle_in(P.Subscribe(packet_id=1,
                             topic_filters=[("$SYS/brokers/#", {"qos": 0})]))
    ch.outbox.clear()
    app.sys.heartbeat()
    got = [p.topic for p in ch.outbox if isinstance(p, P.Publish)]
    assert any(t.startswith("$SYS/brokers/") for t in got)
    # root wildcard must NOT see $SYS
    ch2 = Channel(app.broker, app.cm)
    ch2.handle_in(P.Connect(proto_ver=P.MQTT_V5, clientid="rootw"))
    ch2.handle_in(P.Subscribe(packet_id=1, topic_filters=[("#", {"qos": 0})]))
    ch2.outbox.clear()
    app.sys.heartbeat()
    assert not [p for p in ch2.outbox if isinstance(p, P.Publish)]


def test_device_failover_is_a_fixed_slot_and_surfaces_everywhere():
    """ISSUE 3 satellite: messages.device_failover (counted by
    broker._device_failover since PR 2) must render at ZERO in the
    prometheus exposition and ride the $SYS metrics heartbeat — a
    counter only visible after the first failover is useless for
    alerting on the first failover."""
    from emqx_tpu.observe.sys import SysHeartbeat

    m = Metrics()
    assert "messages.device_failover" in m.all()      # fixed slot
    text = prometheus.render(m, node="n1")
    assert 'emqx_messages_device_failover{node="n1"} 0' in text
    m.inc("messages.device_failover", 3)
    assert m.val("messages.device_failover") == 3
    msgs = []
    SysHeartbeat("n1", msgs.append, metrics=m).publish_metrics()
    by_topic = {x.topic: x.payload for x in msgs}
    assert by_topic[
        "$SYS/brokers/n1/metrics/messages.device_failover"] == b"3"


def test_latency_histograms_render_and_heartbeat():
    """Histogram-aware Metrics: registered LatencyHistograms render as
    prometheus _bucket/_sum/_count series (cumulative, seconds) and
    publish p50/p99/p999 $SYS latency heartbeat topics."""
    from emqx_tpu.observe.metrics import HIST_EDGES_NS
    from emqx_tpu.observe.sys import SysHeartbeat

    m = Metrics()
    h = m.register_hist("latency.native.ingress_route")
    assert m.register_hist("latency.native.ingress_route") is h  # idem
    for ns in (500, 1_000, 2_000, 1_000_000):
        h.observe(ns)
    text = prometheus.render(m, node="n1")
    base = "emqx_latency_native_ingress_route_seconds"
    assert f"# TYPE {base} histogram" in text
    assert f'{base}_bucket{{node="n1",le="+Inf"}} 4' in text
    assert f'{base}_count{{node="n1"}} 4' in text
    assert f'{base}_sum{{node="n1"}}' in text
    # cumulative: the last finite bucket line carries count<=4 and the
    # le values are ascending seconds
    les = [ln for ln in text.splitlines() if f"{base}_bucket" in ln]
    assert len(les) >= 3
    msgs = []
    SysHeartbeat("n1", msgs.append, metrics=m).publish_latency()
    topics = {x.topic for x in msgs}
    assert "$SYS/brokers/n1/latency/native/ingress_route/p99" in topics
    assert "$SYS/brokers/n1/latency/native/ingress_route/count" in topics
    # an empty histogram publishes nothing
    m2 = Metrics()
    m2.register_hist("latency.native.lane_dwell")
    msgs2 = []
    SysHeartbeat("n1", msgs2.append, metrics=m2).publish_latency()
    assert not msgs2
    # reset clears histograms too
    m.reset()
    assert h.count == 0 and int(h.counts.sum()) == 0


def test_slow_subs_plane_tag():
    """Native-plane ack RTTs rank next to Python-plane deliveries,
    distinguishable by the plane tag."""
    from emqx_tpu.services.slow_subs import SlowSubs

    ss = SlowSubs(threshold_ms=100, top_k=5)
    ss.record("py-client", "a/b", 500)                      # default
    ss.record("native-client", "a/b", 900, plane="native")
    top = ss.top()
    assert top[0].clientid == "native-client"
    assert top[0].plane == "native"
    assert top[1].plane == "python"
