"""Multi-core native plane (round 12): sharded epoll hosts + the
lock-free cross-shard ring.

``NativeBrokerServer(shards=N)`` runs N independent C++ epoll hosts
(one poll thread each) sharing one port via SO_REUSEPORT accept
sharding; the match table replicates and DELIVERY crosses shards over
``native/src/ring.h``'s SPSC rings in the trunk batch layout with
explicit target lists. Covered here:

- shard-prefixed conn ids (bits 56-58) stay globally unique across
  concurrent accept streams;
- cross-shard qos0/qos1 fan-out is BIT-IDENTICAL to a 1-shard oracle
  run of the same topology (delivery sets per subscriber);
- per-topic ordering holds across the ring (one publisher's messages
  arrive in publish order at a subscriber on another shard);
- the degradation ladder: a full ring punts the publish to Python
  BEFORE any side effect (the trunk discipline), nothing is lost;
- demote/promote live-plane handoff works for a conn on a non-zero
  shard (kind-11 records route by the conn's owner);
- durable appends stay exactly-once with publishers on two shards
  racing into one shared store;
- the lane+trunk coexistence edge (this PR's carried satellite): a
  publish matching both a device-lane audience and an eligible remote
  entry trunks the remote leg instead of punting the whole fan-out.
"""

import asyncio
import socket
import struct
import time

import pytest

from emqx_tpu import native

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native lib unavailable")

from emqx_tpu.app import BrokerApp                              # noqa: E402
from emqx_tpu.broker.native_server import NativeBrokerServer    # noqa: E402
from emqx_tpu.mqtt.client import MqttClient                     # noqa: E402
from emqx_tpu.session.persistent import MemStore                # noqa: E402


def run(coro):
    asyncio.run(coro)


async def _settle(seconds=0.5):
    await asyncio.sleep(seconds)


async def _client_on_shard(server, clientid, shard, **kw):
    """Connect an MqttClient and retry until the kernel's SO_REUSEPORT
    hash lands it on ``shard`` (each attempt uses a fresh ephemeral
    source port, so the hash re-rolls). shard=None accepts any."""
    for _ in range(80):
        c = MqttClient(port=server.port, clientid=clientid, **kw)
        await c.connect()
        conn_id = None
        for _ in range(100):
            conn_id = server._fast_conn_of.get(clientid)
            if conn_id is None:
                # non-fast conns (persistent sessions) never enter the
                # fast map: find them in the conn table by clientid
                for cid, conn in list(server.conns.items()):
                    if conn.channel.clientid == clientid:
                        conn_id = cid
                        break
            if conn_id is not None:
                break
            await asyncio.sleep(0.01)
        assert conn_id is not None, f"conn for {clientid} never surfaced"
        if shard is None or native.shard_of(conn_id) == shard:
            return c, conn_id
        await c.close()
        await asyncio.sleep(0.02)
    raise AssertionError(f"could not place {clientid} on shard {shard}")


def _mqtt_connect(cid: bytes) -> bytes:
    vh = b"\x00\x04MQTT\x04\x02\x00\x3c" + struct.pack(">H", len(cid)) + cid
    return bytes([0x10, len(vh)]) + vh


def _mqtt_publish(topic: bytes, payload: bytes, qos=0, pid=0) -> bytes:
    body = struct.pack(">H", len(topic)) + topic
    if qos:
        body += struct.pack(">H", pid)
    body += payload
    return bytes([0x30 | (qos << 1), len(body)]) + body


# -- conn-id namespace --------------------------------------------------------

def test_conn_ids_carry_shard_prefix_and_stay_unique():
    """Every conn id names its owner shard in bits 56-58; concurrent
    accept streams on two shards never collide (each shard mints its
    own sequence under its own prefix)."""
    server = NativeBrokerServer(port=0, app=BrokerApp(), shards=2)
    server.start()

    async def main():
        clients = []
        for i in range(24):
            c = MqttClient(port=server.port, clientid=f"cid{i}")
            await c.connect()
            clients.append(c)
        await _settle(0.3)
        ids = list(server.conns)
        assert len(ids) == 24
        assert len(set(ids)) == 24              # globally unique
        shards = {native.shard_of(i) for i in ids}
        assert shards <= {0, 1}
        # 24 hash-spread conns essentially never all land on one shard
        assert shards == {0, 1}, shards
        # the wrapper routes per-conn ops by this prefix: a bare send
        # through the sharded surface must reach the right host (the
        # wrong host would drop it on an unknown conn id)
        for c in clients:
            await c.close()

    run(main())
    server.stop()


# -- parity vs the 1-shard oracle --------------------------------------------

TOPOLOGY = [                     # (clientid, filter, qos, want_shard)
    ("ps0", "par/+/x", 0, 0),
    ("ps1", "par/a/#", 1, 1),
    ("ps2", "par/a/x", 1, 0),
    ("ps3", "par/b/+", 0, 1),
]
DRIVE = [                        # (publisher idx, topic, qos)
    (0, "par/a/x", 0), (1, "par/a/x", 1), (0, "par/b/y", 0),
    (1, "par/a/z", 1), (0, "par/a/z", 0), (1, "par/b/y", 1),
]


def _drive_topology(shards: int) -> dict:
    """Run TOPOLOGY × DRIVE against a fresh server; returns
    {clientid: sorted [(topic, payload, qos)]} plus the stats."""
    server = NativeBrokerServer(port=0, app=BrokerApp(), shards=shards)
    server.start()
    got: dict = {cid: [] for cid, _, _, _ in TOPOLOGY}

    async def main():
        subs = []
        for cid, filt, qos, want in TOPOLOGY:
            c, _ = await _client_on_shard(
                server, cid, want if shards > 1 else None)
            await c.subscribe(filt, qos=qos)
            subs.append((cid, c))
        pubs = []
        for p in range(2):
            c, _ = await _client_on_shard(
                server, f"pp{p}", p if shards > 1 else None)
            pubs.append(c)
        # earn permits on every driven topic (slow path first)
        for t in {t for _, t, _ in DRIVE}:
            await pubs[0].publish(t, b"warm", qos=1)
            await pubs[1].publish(t, b"warm", qos=1)
        await _settle(0.8)
        for i, (p, topic, qos) in enumerate(DRIVE * 10):
            await pubs[p].publish(topic, f"m{i}".encode(), qos=qos)
        await _settle(0.2)

        async def drain(cid, c):
            while True:
                try:
                    m = await c.recv(timeout=1.2)
                except asyncio.TimeoutError:
                    return
                if m.payload != b"warm":
                    got[cid].append((m.topic, bytes(m.payload), m.qos))

        await asyncio.gather(*(drain(cid, c) for cid, c in subs))
        for _, c in subs:
            await c.close()
        for c in pubs:
            await c.close()

    run(main())
    stats = server.fast_stats()
    server.stop()
    return {cid: sorted(v) for cid, v in got.items()}, stats


def test_cross_shard_qos0_qos1_parity_vs_one_shard_oracle():
    """The same topology (overlapping wildcard filters, mixed qos,
    two publishers) driven on shards=2 and shards=1 must produce
    BIT-IDENTICAL delivery sets per subscriber — and the 2-shard run
    must actually have crossed the ring (placement is forced so every
    publisher has audience on both shards)."""
    oracle, _ = _drive_topology(shards=1)
    sharded, stats = _drive_topology(shards=2)
    assert stats["shard_ring_out"] > 0, stats    # the ring really ran
    assert stats["shard_ring_in"] == stats["shard_ring_out"], stats
    assert stats["shard_ring_full"] == 0, stats
    for cid in oracle:
        assert sharded[cid] == oracle[cid], (
            cid, len(sharded[cid]), len(oracle[cid]))
    # every subscriber saw traffic at all (the parity isn't vacuous):
    # DRIVE x10 = 60 publishes, the narrowest filter matches 20
    assert all(len(v) >= 20 for v in oracle.values()), {
        k: len(v) for k, v in oracle.items()}


def test_per_topic_ordering_across_the_ring():
    """One publisher's numbered stream arrives IN ORDER at a
    subscriber on the other shard: the SPSC ring is FIFO and the
    consumer decodes sequentially, exactly like a trunk link."""
    server = NativeBrokerServer(port=0, app=BrokerApp(), shards=2)
    server.start()

    async def main():
        sub, sub_conn = await _client_on_shard(server, "ord-s", 1)
        await sub.subscribe("ord/t", qos=0)
        pub, pub_conn = await _client_on_shard(server, "ord-p", 0)
        assert native.shard_of(sub_conn) != native.shard_of(pub_conn)
        await pub.publish("ord/t", b"warm", qos=1)
        await sub.recv(timeout=10)
        await _settle(0.8)
        n = 400
        for i in range(n):
            await pub.publish("ord/t", struct.pack("<I", i), qos=0)
        got = []
        while len(got) < n:
            m = await sub.recv(timeout=10)
            got.append(struct.unpack("<I", m.payload)[0])
        assert got == list(range(n)), got[:20]
        st = server.fast_stats()
        assert st["shard_ring_out"] >= n, st
        await sub.close(); await pub.close()

    run(main())
    server.stop()


# -- degradation ladder -------------------------------------------------------

def test_ring_full_degrades_to_punt_before_side_effects():
    """Raw two-host group with the CONSUMER shard never polled: its
    inbound ring fills (256 sealed batches), after which a publish
    with cross-shard audience degrades ring-full → punt → Python as a
    kind-2 frame event — no partial fan-out, nothing lost."""
    group = native.NativeShardGroup(2)
    h0 = native.NativeHost(port=0, max_size=1 << 16)
    h1 = native.NativeHost(port=0, max_size=1 << 16)
    try:
        h0.join_group(group, 0)
        h1.join_group(group, 1)
        list(h1.poll(20))            # register the doorbell, then park

        ids = []

        def pump(host, want_opens=0, want_frames=0, deadline_s=5.0):
            frames = []
            t0 = time.time()
            while time.time() - t0 < deadline_s:
                for kind, conn, payload in host.poll(20):
                    if kind == native.EV_OPEN:
                        ids.append(conn)
                    elif kind == native.EV_FRAME:
                        frames.append((conn, payload))
                if len(ids) >= want_opens and len(frames) >= want_frames:
                    break
            return frames

        pub = socket.create_connection(("127.0.0.1", h0.port))
        pump(h0, want_opens=1)
        pub.sendall(_mqtt_connect(b"rfp"))
        pump(h0, want_opens=1, want_frames=1)
        (pub_id,) = ids
        assert native.shard_of(pub_id) == 0
        # a subscriber conn living on shard 1: drain its OPEN once so
        # the conn exists over there, then park h1 forever
        sub = socket.create_connection(("127.0.0.1", h1.port))
        t0 = time.time()
        sub_id = None
        while sub_id is None and time.time() - t0 < 5:
            for kind, conn, payload in h1.poll(20):
                if kind == native.EV_OPEN:
                    sub_id = conn
        assert sub_id is not None and native.shard_of(sub_id) == 1
        # replicate the table op on the PRODUCER shard (the broadcast
        # discipline) and authorize the publisher
        h0.sub_add(sub_id, "rf/t", 0, 0)
        h0.enable_fast(pub_id, 4, 0)
        h0.permit(pub_id, "rf/t")
        list(h0.poll(20))

        # one publish per poll cycle seals one ring batch; 256 slots
        # and a never-polling consumer fill the ring, then the ladder
        # kicks in: ring-full -> punt -> Python (kind-2 frame events)
        punts = []
        sent = 0
        for i in range(300):
            pub.sendall(_mqtt_publish(b"rf/t", b"x%d" % i))
            sent += 1
            for kind, conn, payload in h0.poll(20):
                if kind == native.EV_FRAME:
                    punts.append(payload)
            st = h0.stats()
            if st["shard_ring_full"] > 0 and punts:
                break
        st = h0.stats()
        assert st["shard_ring_full"] > 0, (sent, st)
        assert st["punts"] > 0, st
        assert punts and punts[-1].startswith(bytes([0x30])), punts[-1][:4]
        # accounting holds: every publish either shipped or punted
        assert st["shard_ring_out"] + len(punts) >= sent, (sent, st)
        pub.close()
        sub.close()
        for _ in range(3):
            list(h0.poll(10))
            list(h1.poll(10))
    finally:
        h0.destroy()
        h1.destroy()
        group.destroy()


# -- live plane handoff on a non-zero shard ----------------------------------

def test_demote_promote_handoff_on_nonzero_shard():
    """kDisableFast on a shard-1 conn emits its kind-11 handoff from
    shard 1's poll thread and the Python session adopts it; promote()
    re-enables the fast plane through the sharded control surface."""
    server = NativeBrokerServer(port=0, app=BrokerApp(), shards=2)
    server.start()

    async def main():
        sub, _ = await _client_on_shard(server, "hs-s", 0)
        await sub.subscribe("hs/t", qos=1)
        pub, pub_conn = await _client_on_shard(server, "hs-p", 1)
        assert native.shard_of(pub_conn) == 1
        await pub.publish("hs/t", b"warm", qos=1)
        await sub.recv(timeout=10)
        await _settle(0.8)
        h0 = server.fast_stats()["handoffs"]
        server.host.disable_fast(pub_conn)
        t0 = time.monotonic()
        while time.monotonic() - t0 < 5:
            if server.fast_stats()["handoffs"] > h0:
                break
            await asyncio.sleep(0.05)
        assert server.fast_stats()["handoffs"] > h0
        await _settle(0.3)
        conn = server.conns[pub_conn]
        assert not conn.fast
        # the demoted publisher keeps publishing through Python
        await pub.publish("hs/t", b"slow", qos=1)
        assert (await sub.recv(timeout=10)).payload == b"slow"
        # promotion re-splits the budget and returns to the fast path
        assert server.promote("hs-p")
        assert conn.fast
        await _settle(0.8)
        await pub.publish("hs/t", b"fast", qos=1)
        assert (await sub.recv(timeout=10)).payload == b"fast"
        await sub.close(); await pub.close()

    run(main())
    server.stop()


# -- durable plane under concurrent producers --------------------------------

def test_durable_append_with_publishers_on_two_shards():
    """Publishers on BOTH shards matching one offline persistent
    session: every message lands in the shared store exactly once
    (atomic guid allocation + the store's internal mutex) and the
    resume replays the union exactly once."""
    app = BrokerApp(persistent_store=MemStore())
    server = NativeBrokerServer(port=0, app=app, shards=2)
    server.start()

    async def main():
        ps = MqttClient(port=server.port, clientid="ds-ps",
                        clean_start=False, proto_ver=5,
                        properties={"Session-Expiry-Interval": 300})
        await ps.connect()
        await ps.subscribe("ds/t", qos=1)
        p0, c0 = await _client_on_shard(server, "ds-p0", 0)
        p1, c1 = await _client_on_shard(server, "ds-p1", 1)
        assert native.shard_of(c0) != native.shard_of(c1)
        await p0.publish("ds/t", b"warm0", qos=1)
        await ps.recv(timeout=10)
        await p1.publish("ds/t", b"warm1", qos=1)
        await ps.recv(timeout=10)
        await _settle(0.8)
        await ps.close()                     # offline, session kept
        await _settle(0.3)
        want = set()
        for i in range(20):
            await p0.publish("ds/t", f"a{i}".encode(), qos=1)
            await p1.publish("ds/t", f"b{i}".encode(), qos=1)
            want.add(f"a{i}".encode())
            want.add(f"b{i}".encode())
        await _settle(0.6)
        st = server.fast_stats()
        assert st["durable_in"] >= 40, st
        assert st["punts"] <= 8, st          # the fast path held
        ps2 = MqttClient(port=server.port, clientid="ds-ps",
                         clean_start=False, proto_ver=5,
                         properties={"Session-Expiry-Interval": 300})
        await ps2.connect()
        got = []
        for _ in range(len(want)):
            got.append(bytes((await ps2.recv(timeout=10)).payload))
        assert sorted(got) == sorted(want), (len(got), len(want))
        with pytest.raises(asyncio.TimeoutError):   # exactly once
            await ps2.recv(timeout=0.8)
        await ps2.close()
        await p0.close(); await p1.close()

    run(main())
    server.stop()


# -- lane + trunk coexistence (carried edge) ---------------------------------

def test_lane_plus_trunk_coexistence_trunks_remote_leg():
    """A publish matching BOTH a device-lane audience and an eligible
    remote entry used to punt wholesale (the device model can't see
    remote routes). Now the frame parks on the lane and LaneDeliver
    enqueues the trunk leg next to the local fan-out — zero punts,
    both legs delivered. Raw two-host trunk pair, lane verdicts faked
    through host.lane_deliver (the product pump's seam)."""
    ha = native.NativeHost(port=0, max_size=1 << 16)
    hb = native.NativeHost(port=0, max_size=1 << 16)
    try:
        hb.trunk_listen("127.0.0.1", 0)

        def pump(host, bucket, deadline_s=0.3):
            t0 = time.time()
            while time.time() - t0 < deadline_s:
                for ev in host.poll(20):
                    bucket.append(ev)
            return bucket

        ha.trunk_connect(7, "127.0.0.1", hb.trunk_port)
        evs_a, evs_b = [], []
        t0 = time.time()
        up = False
        while not up and time.time() - t0 < 5:
            pump(ha, evs_a, 0.05)
            pump(hb, evs_b, 0.05)
            up = any(k == native.EV_TRUNK and p[:1] == bytes([native.TRUNK_UP])
                     for k, _, p in evs_a)
        assert up, evs_a

        # publisher + local subscriber on A; remote route to B; a
        # local subscriber on B receives the trunked leg natively
        pub = socket.create_connection(("127.0.0.1", ha.port))
        sub_a = socket.create_connection(("127.0.0.1", ha.port))
        sub_b = socket.create_connection(("127.0.0.1", hb.port))
        pump(ha, evs_a, 0.2); pump(hb, evs_b, 0.2)
        pub.sendall(_mqtt_connect(b"ltp"))
        sub_a.sendall(_mqtt_connect(b"lts"))
        sub_b.sendall(_mqtt_connect(b"ltb"))
        pump(ha, evs_a, 0.2); pump(hb, evs_b, 0.2)
        a_ids = [c for k, c, _ in evs_a if k == native.EV_OPEN]
        b_ids = [c for k, c, _ in evs_b if k == native.EV_OPEN]
        assert len(a_ids) >= 2 and len(b_ids) >= 1
        pub_id, sub_a_id = a_ids[0], a_ids[1]
        sub_b_id = b_ids[-1]

        ha.enable_fast(pub_id, 4, 0)
        ha.enable_fast(sub_a_id, 4, 0)
        ha.sub_add(sub_a_id, "lt/t", 0, 0)
        ha.trunk_route_add(7, "lt/t")
        hb.enable_fast(sub_b_id, 4, 0)
        hb.sub_add(sub_b_id, "lt/t", 0, 0)
        ha.permit(pub_id, "lt/t")
        ha.set_lane(True)
        list(ha.poll(20)); list(hb.poll(20))

        pub.sendall(_mqtt_publish(b"lt/t", b"both"))
        lane_seq = None
        t0 = time.time()
        while lane_seq is None and time.time() - t0 < 5:
            for k, c, p in ha.poll(20):
                if k == native.EV_LANE:
                    lane_seq = c
        assert lane_seq is not None, "remote entry forced a punt"
        filt = b"lt/t"
        ha.lane_deliver(struct.pack("<IQBH", 1, lane_seq, 0, 1)
                        + struct.pack("<H", len(filt)) + filt)
        for _ in range(5):
            list(ha.poll(20))    # apply the verdict + flush the trunk
        # local leg on A
        sub_a.settimeout(5)
        data = sub_a.recv(4096)
        assert b"both" in data, data
        # trunked leg fans out natively on B
        t0 = time.time()
        got_b = b""
        sub_b.settimeout(0.2)
        while b"both" not in got_b and time.time() - t0 < 5:
            pump(hb, evs_b, 0.05)
            try:
                got_b += sub_b.recv(4096)
            except socket.timeout:
                pass
        assert b"both" in got_b, got_b
        st = ha.stats()
        assert st["lane_punts"] == 0, st
        assert st["trunk_out"] >= 1, st
        assert st["fast_out"] >= 1, st
        for s in (pub, sub_a, sub_b):
            s.close()
        for _ in range(3):
            list(ha.poll(10)); list(hb.poll(10))
    finally:
        ha.destroy()
        hb.destroy()
