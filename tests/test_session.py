"""Session layer tests — mirrors emqx_inflight_SUITE, emqx_mqueue_SUITE,
emqx_session_SUITE."""

import pytest

from emqx_tpu.core.message import Message, SubOpts
from emqx_tpu.mqtt import packet as P
from emqx_tpu.session.inflight import Inflight
from emqx_tpu.session.mqueue import MQueue, MQueueOpts
from emqx_tpu.session.session import Session, SessionError


def msg(topic="t", qos=1, **kw):
    return Message(topic=topic, qos=qos, **kw)


# -- inflight ---------------------------------------------------------------

def test_inflight_window():
    inf = Inflight(max_size=2)
    inf.insert(1, "a")
    inf.insert(2, "b")
    assert inf.is_full()
    with pytest.raises(KeyError):
        inf.insert(1, "dup")
    assert inf.delete(1) == "a"
    assert not inf.is_full()
    assert inf.peek_oldest() == (2, "b")


# -- mqueue -----------------------------------------------------------------

def test_mqueue_drop_oldest():
    q = MQueue(MQueueOpts(max_len=3))
    dropped = [q.insert(msg(payload=bytes([i]))) for i in range(5)]
    assert dropped[:3] == [None, None, None]
    assert dropped[3].payload == b"\x00"    # oldest dropped
    assert dropped[4].payload == b"\x01"
    assert q.dropped == 2
    assert [m.payload for m in [q.pop(), q.pop(), q.pop()]] == [b"\x02", b"\x03", b"\x04"]
    assert q.pop() is None


def test_mqueue_qos0_bypass():
    q = MQueue(MQueueOpts(store_qos0=False))
    d = q.insert(msg(qos=0))
    assert d is not None and len(q) == 0
    assert q.insert(msg(qos=1)) is None and len(q) == 1


def test_mqueue_priorities():
    q = MQueue(MQueueOpts(priorities={"hi": 10, "lo": 1}, shift_multiplier=100))
    q.insert(msg(topic="lo", payload=b"1"))
    q.insert(msg(topic="hi", payload=b"2"))
    q.insert(msg(topic="plain", payload=b"3"))
    assert q.pop().topic == "hi"
    assert q.pop().topic == "lo"
    assert q.pop().topic == "plain"


# -- session QoS flows ------------------------------------------------------

def make_session(**kw):
    s = Session(clientid="c1", max_inflight=2, **kw)
    s.subscribe("t", SubOpts(qos=2))
    s.subscribe("t0", SubOpts(qos=0))
    return s


def test_deliver_qos0():
    s = make_session()
    out = s.deliver([("t0", msg(topic="t0", qos=0))])
    assert len(out) == 1 and out[0].qos == 0 and out[0].packet_id is None
    assert s.inflight.is_empty()


def test_deliver_qos1_ack_cycle():
    s = make_session()
    out = s.deliver([("t", msg(qos=1))])
    pid = out[0].packet_id
    assert out[0].qos == 1 and pid is not None
    assert len(s.inflight) == 1
    assert s.puback(pid) == []
    assert s.inflight.is_empty()
    with pytest.raises(SessionError):
        s.puback(pid)


def test_deliver_qos2_full_cycle():
    s = make_session()
    out = s.deliver([("t", msg(qos=2))])
    pid = out[0].packet_id
    rel = s.pubrec(pid)
    assert isinstance(rel, P.PubRel) and rel.packet_id == pid
    # pubrec twice → error (phase moved on)
    with pytest.raises(SessionError):
        s.pubrec(pid)
    assert s.pubcomp(pid) == []
    assert s.inflight.is_empty()


def test_backpressure_enqueue_and_dequeue():
    s = make_session()
    out = s.deliver([("t", msg(qos=1, payload=bytes([i]))) for i in range(5)])
    assert len(out) == 2                      # window = 2
    assert len(s.mqueue) == 3
    nxt = s.puback(out[0].packet_id)
    assert len(nxt) == 1 and nxt[0].payload == b"\x02"
    assert len(s.mqueue) == 2


def test_min_qos_rule():
    s = Session(clientid="c")
    s.subscribe("q1", SubOpts(qos=1))
    out = s.deliver([("q1", msg(topic="q1", qos=2))])
    assert out[0].qos == 1                     # min(sub_qos, msg_qos)


def test_no_local():
    s = Session(clientid="me")
    s.subscribe("t", SubOpts(qos=1, nl=1))
    assert s.deliver([("t", msg(qos=1, from_="me"))]) == []
    assert len(s.deliver([("t", msg(qos=1, from_="other"))])) == 1


def test_qos2_receive_dedup():
    s = make_session()
    m = msg(qos=2)
    s.publish_in(10, m)
    with pytest.raises(SessionError) as ei:
        s.publish_in(10, m)
    assert ei.value.rc == P.RC_PACKET_IDENTIFIER_IN_USE
    s.pubrel_in(10)
    s.publish_in(10, m)   # free again after PUBREL
    with pytest.raises(SessionError):
        s.pubrel_in(99)


def test_awaiting_rel_quota_and_expiry():
    s = Session(clientid="c", max_awaiting_rel=2, await_rel_timeout_ms=100)
    s.publish_in(1, msg(qos=2), now=1000)
    s.publish_in(2, msg(qos=2), now=1000)
    with pytest.raises(SessionError) as ei:
        s.publish_in(3, msg(qos=2), now=1000)
    assert ei.value.rc == P.RC_RECEIVE_MAXIMUM_EXCEEDED
    assert s.expire_awaiting_rel(now=1100) == 2
    s.publish_in(3, msg(qos=2), now=1101)


def test_retry_redelivers_with_dup():
    s = make_session(retry_interval_ms=100)
    out = s.deliver([("t", msg(qos=1))], now=1000)
    pid = out[0].packet_id
    assert s.retry(now=1050) == []            # not yet
    redel = s.retry(now=1200)
    assert len(redel) == 1 and redel[0].dup and redel[0].packet_id == pid
    # QoS2 pubrel phase retries as PUBREL
    out2 = s.deliver([("t", msg(qos=2))], now=1200)
    s.pubrec(out2[0].packet_id, now=1200)
    redel2 = s.retry(now=1400)
    assert any(isinstance(p, P.PubRel) for p in redel2)


def test_packet_id_wraps_and_skips_inflight():
    # the session's pid space is [1, 32767]: [32768, 65535] belongs to
    # the native host's fast-path deliveries on the same wire connection
    # (native/src/host.cc kNativePidBase), so PUBACKs route by range
    s = Session(clientid="c", max_inflight=0)
    s._next_pkt_id = Session.PKT_ID_SPACE - 1
    assert s.next_packet_id() == Session.PKT_ID_SPACE
    assert s.next_packet_id() == 1
    s.inflight.insert(2, "x")
    assert s.next_packet_id() == 3


def test_unsubscribe_then_late_delivery_dropped():
    s = make_session()
    s.unsubscribe("t")
    assert s.deliver([("t", msg(qos=1))]) == []
    with pytest.raises(SessionError):
        s.unsubscribe("t")


def test_pending_for_resume():
    s = make_session()
    out = s.deliver([("t", msg(qos=1, payload=bytes([i]))) for i in range(4)])
    pend = s.pending_for_resume()
    assert len(pend) == 4   # 2 inflight + 2 queued


def test_mqueue_priority_eviction_when_full():
    q = MQueue(MQueueOpts(max_len=1, priorities={"hi": 5}))
    q.insert(msg(topic="plain"))
    dropped = q.insert(msg(topic="hi"))      # evicts the low-prio resident
    assert dropped is not None and dropped.topic == "plain"
    assert q.pop().topic == "hi"
    # and an incoming message below everything queued is itself dropped
    q2 = MQueue(MQueueOpts(max_len=1, priorities={"hi": 5}))
    q2.insert(msg(topic="hi"))
    d2 = q2.insert(msg(topic="plain"))
    assert d2 is not None and d2.topic == "plain"
    assert q2.pop().topic == "hi"


def test_retry_preserves_subid_and_rap():
    s = Session(clientid="c", retry_interval_ms=10)
    s.subscribe("t", SubOpts(qos=1, rap=1, subid=7))
    m = msg(qos=1)
    m = m.set_flag("retain", True)
    out = s.deliver([("t", m)], now=0)
    assert out[0].retain and out[0].properties["Subscription-Identifier"] == [7]
    redel = s.retry(now=1000)
    assert redel[0].dup
    assert redel[0].retain is True
    assert redel[0].properties["Subscription-Identifier"] == [7]


def test_mqtt5_receive_maximum_caps_window():
    from emqx_tpu.app import BrokerApp
    from emqx_tpu.broker.channel import Channel
    from emqx_tpu.mqtt import packet as P

    app = BrokerApp()
    sent = []
    ch = Channel(app.broker, app.cm, send=sent.extend)
    connack = ch.handle_in(P.Connect(
        proto_ver=P.MQTT_V5, clientid="rm1",
        properties={"Receive-Maximum": 2}))[0]
    assert ch.session.max_inflight == 2
    assert connack.properties["Receive-Maximum"] == 2
    assert connack.properties["Topic-Alias-Maximum"] == 65535
    ch.handle_in(P.Subscribe(packet_id=1,
                             topic_filters=[("w/#", {"qos": 1})]))
    sent.clear()
    from emqx_tpu.core.message import Message
    for i in range(5):
        app.cm.dispatch(app.broker.publish(
            Message(topic="w/x", payload=str(i).encode(), qos=1)))
    pubs = [p for p in sent if isinstance(p, P.Publish)]
    assert len(pubs) == 2                      # window capped at RM=2
    assert len(ch.session.mqueue) == 3         # rest queued


def test_mqtt5_message_expiry_remaining_interval():
    from emqx_tpu.core.message import Message, SubOpts, now_ms
    from emqx_tpu.session.session import Session

    s = Session(clientid="me1")
    s.subscribe("t", SubOpts(qos=0))
    old = Message(topic="t", payload=b"x", qos=0,
                  headers={"properties": {"Message-Expiry-Interval": 60}})
    old.timestamp = now_ms() - 10_000          # 10s on the shelf
    (pkt,) = s.deliver([("t", old)])
    assert 49 <= pkt.properties["Message-Expiry-Interval"] <= 51
    # fully expired → dropped
    dead = Message(topic="t", payload=b"y", qos=0,
                   headers={"properties": {"Message-Expiry-Interval": 5}})
    dead.timestamp = now_ms() - 6_000
    assert s.deliver([("t", dead)]) == []
