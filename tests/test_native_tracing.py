"""Native distributed tracing + degradation ledger (ISSUE 8).

A deterministic 1-in-2^shift publish sampler in the C++ host tags
fast-path publishes with 64-bit trace ids that propagate through every
native seam — cross-shard ring entries, trunk BATCH records (wire v1,
negotiated away against old peers), durable MSG-BATCH records — while
the message stays on the fast path. Each plane emits compact kind-12
span events a Python SpanCollector stitches into per-message
timelines; every degradation-ladder decision emits a structured ledger
reason event. Covered here:

- sampler determinism (the global ticker counts natively-consumed
  publishes; 1-in-2^shift is exact, not probabilistic);
- local span stitching: one sampled qos1 publish = one assembled trace
  whose stage ordering matches the oracle (ingress -> deliver_write ->
  route -> ack);
- cross-shard parity: the trace id rides the ring; the consumer shard
  re-joins the timeline (ring_cross -> deliver_write) and the stitched
  ordering matches the oracle;
- cross-node (two-host trunk pair) parity: the id rides the trunk wire
  and BOTH nodes' collectors assemble one trace (trunk_flush on A,
  trunk_recv + deliver_write on B);
- old-peer downshift: a v0 peer never sees trace ids, deliveries stay
  bit-identical (lossless strip);
- the degradation ledger: trunk-down punts produce structured
  trunk_punt events with per-reason fixed metric slots, and the
  Python-plane reasons (device_failover, store_degraded) fold into the
  same ledger; the mgmt endpoints page both rings;
- native-mode clientid traces: the conn stays on the fast path
  (punts_trace == 0) while sampled span timelines land on the trace
  log — tracing no longer turns off the thing being observed;
- the durable store persists trace ids (restart survival) and a resume
  replay re-joins the timeline with a replay span;
- the escape hatches (tracing=False / telemetry=False).
"""

import asyncio
import time

import pytest

from emqx_tpu import native

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native lib unavailable")

from emqx_tpu.app import BrokerApp                              # noqa: E402
from emqx_tpu.broker.native_server import NativeBrokerServer    # noqa: E402
from emqx_tpu.cluster.node import ClusterNode                   # noqa: E402
from emqx_tpu.cluster.transport import LocalBus                 # noqa: E402
from emqx_tpu.mqtt.client import MqttClient                     # noqa: E402
from emqx_tpu.session.persistent import MemStore                # noqa: E402


def run(coro):
    asyncio.run(coro)


def _wait(pred, timeout=8.0, step=0.02):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if pred():
            return True
        time.sleep(step)
    return False


async def _await(pred, timeout=8.0, step=0.05):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if pred():
            return True
        await asyncio.sleep(step)
    return False


async def _warm(pub, sub, topic, qos=0, settle=0.6):
    """First publish rides the Python lane and earns the permit; the
    grant lands once the pipeline is idle."""
    await pub.publish(topic, b"warm", qos=qos)
    await sub.recv(timeout=10)
    await asyncio.sleep(settle)


# -- sampler ------------------------------------------------------------------


def test_sampler_determinism():
    """shift=2 samples EXACTLY 1-in-4 natively-consumed publishes (the
    global ticker, not a coin flip): 16 fast publishes -> 4 traces."""
    server = NativeBrokerServer(port=0, app=BrokerApp(),
                                trace_sample_shift=2)
    server.start()

    async def main():
        sub = MqttClient(port=server.port, clientid="sd-s")
        await sub.connect()
        await sub.subscribe("sd/t", qos=0)
        pub = MqttClient(port=server.port, clientid="sd-p")
        await pub.connect()
        await _warm(pub, sub, "sd/t")
        for i in range(16):
            await pub.publish("sd/t", b"m%d" % i, qos=0)
            await sub.recv(timeout=10)
        assert await _await(
            lambda: server.fast_stats()["fast_in"] >= 16)
        st = server.fast_stats()
        assert st["traced_pubs"] == 4, st
        assert await _await(lambda: len(server.spans) == 4)
        # every assembled trace has the local-qos0 oracle stage order
        for tid, spans in server.spans.recent(4):
            assert [s[1] for s in spans] == [
                "ingress", "deliver_write", "route"], (tid, spans)
        await sub.close(); await pub.close()

    run(main())
    server.stop()


def test_local_qos1_trace_stitching_and_exemplars():
    """One sampled qos1 publish yields exactly one assembled trace:
    ingress -> deliver_write -> route -> ack, t_ns non-decreasing,
    ingress aux = the publisher's conn id — and the stitched trace
    hangs exemplars off the stage histograms in prometheus."""
    app = BrokerApp()
    server = NativeBrokerServer(port=0, app=app, trace_sample_shift=0)
    server.start()

    async def main():
        sub = MqttClient(port=server.port, clientid="lt-s")
        await sub.connect()
        await sub.subscribe("lt/t", qos=1)
        pub = MqttClient(port=server.port, clientid="lt-p")
        await pub.connect()
        await _warm(pub, sub, "lt/t", qos=1)
        await pub.publish("lt/t", b"one", qos=1)
        await sub.recv(timeout=10)
        assert await _await(lambda: any(
            "ack" in server.spans.stages(tid)
            for tid, _ in server.spans.recent(4)))
        tid, spans = next(
            (t, s) for t, s in server.spans.recent(4)
            if "ack" in [x[1] for x in s])
        stages = [s[1] for s in spans]
        assert stages == ["ingress", "deliver_write", "route", "ack"], spans
        ts = [s[0] for s in spans]
        assert ts == sorted(ts)
        ingress = spans[0]
        assert ingress[4] == server._fast_conn_of["lt-p"]   # aux
        # exemplars: the route span closed the ingress_route duration —
        # rendered only under the OpenMetrics flag (the default 0.0.4
        # scrape must stay parseable by classic Prometheus)
        out = app.prometheus(openmetrics=True)
        assert "trace_id=" in out
        assert "trace_id=" not in app.prometheus()
        # the queryable ring serves the same trace
        rec = server.spans_recent(8)
        assert any(r["trace_id"] == f"{tid:016x}" for r in rec), rec
        await sub.close(); await pub.close()

    run(main())
    server.stop()


# -- cross-shard --------------------------------------------------------------


async def _client_on_shard(server, clientid, shard, **kw):
    """Reconnect until SO_REUSEPORT lands the conn on ``shard``."""
    for _ in range(80):
        c = MqttClient(port=server.port, clientid=clientid, **kw)
        await c.connect()
        conn_id = None
        for _ in range(100):
            conn_id = server._fast_conn_of.get(clientid)
            if conn_id is None:
                for cid, conn in list(server.conns.items()):
                    if conn.channel.clientid == clientid:
                        conn_id = cid
                        break
            if conn_id is not None:
                break
            await asyncio.sleep(0.01)
        assert conn_id is not None, f"conn for {clientid} never surfaced"
        if shard is None or native.shard_of(conn_id) == shard:
            return c, conn_id
        await c.close()
        await asyncio.sleep(0.02)
    raise AssertionError(f"could not place {clientid} on shard {shard}")


def test_cross_shard_span_stitching_parity():
    """A sampled publish whose subscriber lives on ANOTHER shard yields
    ONE assembled trace: the id rides the ring entry and the consumer
    shard re-joins the timeline — ingress/route on the publisher's
    shard, ring_cross/deliver_write on the subscriber's, in that
    order."""
    server = NativeBrokerServer(port=0, app=BrokerApp(), shards=2,
                                trace_sample_shift=0)
    server.start()

    async def main():
        sub, sub_conn = await _client_on_shard(server, "xs-s", None)
        await sub.subscribe("xs/t", qos=0)
        sshard = native.shard_of(sub_conn)
        pub, pub_conn = await _client_on_shard(server, "xs-p",
                                               1 - sshard)
        pshard = native.shard_of(pub_conn)
        assert pshard != sshard
        await _warm(pub, sub, "xs/t")
        n0 = len(server.spans)
        await pub.publish("xs/t", b"cross", qos=0)
        m = await sub.recv(timeout=10)
        assert m.payload == b"cross"
        # wait for the FULL stage set, not just the consumer shard's
        # half: the two shards flush kind-12 batches on their OWN poll
        # cycles, and under load the subscriber shard's ring_cross/
        # deliver_write can fold BEFORE the publisher shard's
        # ingress/route batch lands (deflaked in round 14 — the
        # timeline is assembled from both, so assert once both arrived)
        want = {"ingress", "route", "ring_cross", "deliver_write"}
        assert await _await(lambda: len(server.spans) > n0 and any(
            want <= set(server.spans.stages(tid))
            for tid, _ in server.spans.recent(2))), server.spans.recent(2)
        tid, spans = next(
            (t, s) for t, s in server.spans.recent(2)
            if want <= {x[1] for x in s})
        stages = [s[1] for s in spans]
        shards = {s[1]: s[2] for s in spans}
        assert stages == ["ingress", "route", "ring_cross",
                          "deliver_write"], spans
        assert shards["ingress"] == pshard
        assert shards["route"] == pshard
        assert shards["ring_cross"] == sshard
        assert shards["deliver_write"] == sshard
        # ring_cross aux names the PRODUCING shard
        aux = {s[1]: s[4] for s in spans}
        assert aux["ring_cross"] == pshard
        ts = [s[0] for s in spans]
        assert ts == sorted(ts)
        await sub.close(); await pub.close()

    run(main())
    server.stop()


# -- cross-node (trunk pair) --------------------------------------------------


class _TracedPair:
    """Two ClusterNodes each fronted by a native server with the trace
    sampler at 1-in-1 (the test_native_trunk fixture + tracing)."""

    def __init__(self, suffix: str, b_wire_version: int = None):
        self.fabric = LocalBus.Fabric()
        self.nodes = []
        self.servers = []
        for name in (f"tA{suffix}", f"tB{suffix}"):
            node = ClusterNode(name, LocalBus(name, self.fabric))
            srv = NativeBrokerServer(port=0, app=node.app, trunk_port=0,
                                     trace_sample_shift=0)
            if name.startswith("tB") and b_wire_version is not None:
                # simulate an old peer: cap B's advertised wire version
                # BEFORE any link negotiates
                for h in srv.hosts:
                    h.set_trunk_wire(b_wire_version)
            node.attach_native(srv)
            srv.start()
            self.nodes.append(node)
            self.servers.append(srv)
        self.nodes[1].join([self.nodes[0].name])

    @property
    def a(self):
        return self.servers[0]

    @property
    def b(self):
        return self.servers[1]

    def sync(self):
        for n in self.nodes:
            n.flush()

    def wait_trunks_up(self, timeout=8.0):
        def both_up():
            return (self.a.trunk_peer_status().get(self.nodes[1].name)
                    and self.b.trunk_peer_status().get(self.nodes[0].name))
        assert _wait(both_up, timeout), (
            self.a.trunk_peer_status(), self.b.trunk_peer_status())

    def stop(self):
        for s in self.servers:
            s.stop()
        for n in self.nodes:
            n.transport.close()


def test_two_node_trunk_span_stitching():
    """Cross-node parity: one sampled publish on node A delivered over
    the trunk to a subscriber on node B yields ONE trace id known to
    BOTH collectors; the merged timeline orders ingress/route/
    trunk_flush (A) before trunk_recv/deliver_write (B)."""
    pair = _TracedPair("st")
    try:
        async def main():
            sub = MqttClient(port=pair.b.port, clientid="tn-s")
            await sub.connect()
            await sub.subscribe("tn/t", qos=0)
            pair.sync()
            pair.wait_trunks_up()
            pub = MqttClient(port=pair.a.port, clientid="tn-p")
            await pub.connect()
            await _warm(pub, sub, "tn/t")
            na = len(pair.a.spans)
            await pub.publish("tn/t", b"xnode", qos=0)
            m = await sub.recv(timeout=10)
            assert m.payload == b"xnode"
            assert await _await(lambda: len(pair.a.spans) > na)
            # the newest A-side trace that flushed onto the trunk
            tid = next(t for t, s in pair.a.spans.recent(4)
                       if "trunk_flush" in [x[1] for x in s])
            assert await _await(
                lambda: "deliver_write" in pair.b.spans.stages(tid)), (
                pair.b.spans.recent(4))
            merged = sorted(
                [(t, st, sh, "A", aux) for t, st, sh, _n, aux
                 in pair.a.spans.trace(tid)]
                + [(t, st, sh, "B", aux) for t, st, sh, _n, aux
                   in pair.b.spans.trace(tid)])
            stages = [(s[1], s[3]) for s in merged]
            assert stages == [("ingress", "A"), ("route", "A"),
                              ("trunk_flush", "A"), ("trunk_recv", "B"),
                              ("deliver_write", "B")], merged
            await sub.close(); await pub.close()

        run(main())
    finally:
        pair.stop()


def test_old_peer_downshift_strips_trace_ids_losslessly():
    """Against a peer capped at wire v0 the dialer emits v0 entries:
    trace ids are STRIPPED (no trunk_flush/trunk_recv spans, no ids on
    B) while every message still arrives intact — the downshift is
    lossless for the data plane."""
    pair = _TracedPair("dn", b_wire_version=0)
    try:
        async def main():
            sub = MqttClient(port=pair.b.port, clientid="dn-s")
            await sub.connect()
            await sub.subscribe("dn/t", qos=0)
            pair.sync()
            pair.wait_trunks_up()
            pub = MqttClient(port=pair.a.port, clientid="dn-p")
            await pub.connect()
            await _warm(pub, sub, "dn/t")
            payloads = [b"d%03d" % i for i in range(10)]
            for p in payloads:
                await pub.publish("dn/t", p, qos=0)
            got = []
            while len(got) < len(payloads):
                m = await sub.recv(timeout=8)
                got.append(m.payload)
            assert got == payloads          # lossless, in order
            st_a = pair.a.fast_stats()
            assert st_a["trunk_out"] >= 10, st_a    # still trunked
            # still sampled (shift 0, though pipelined publishes share
            # poll cycles so the per-cycle sampler cap clips the count)
            assert st_a["traced_pubs"] >= 1, st_a
            # A sampled every publish but no trunk_flush span exists
            # (the entry went out v0), and B never saw a trace id
            for tid, spans in pair.a.spans.recent(16):
                assert "trunk_flush" not in [s[1] for s in spans], spans
                assert pair.b.spans.trace(tid) == []
            await sub.close(); await pub.close()

        run(main())
    finally:
        pair.stop()


# -- degradation ledger -------------------------------------------------------


def test_ledger_trunk_punt_events_and_mgmt():
    """A down trunk degrades publishes trunk->punt->Python; every such
    decision folds into ONE structured ledger entry per poll cycle
    (reason=trunk_punt, deciding peer in aux) plus the fixed
    messages.ledger.trunk_punt slot, and the mgmt endpoints page the
    ring. Python-plane reasons fold into the SAME ledger."""
    from emqx_tpu.mgmt.api import ManagementApi

    app = BrokerApp()
    server = NativeBrokerServer(port=0, app=app, trunk_port=0,
                                trace_sample_shift=0)
    server.start()

    async def main():
        sub = MqttClient(port=server.port, clientid="lg-s")
        await sub.connect()
        await sub.subscribe("lg/t", qos=0)
        pub = MqttClient(port=server.port, clientid="lg-p")
        await pub.connect()
        await _warm(pub, sub, "lg/t")
        # a trunk-registered peer whose link can never come up: the
        # remote entry degrades matching publishes to punts
        app.broker.router.add_route("lg/t", "ghost")
        server.trunk_register("ghost", "127.0.0.1", 1)  # dead port
        await asyncio.sleep(0.3)
        for i in range(6):
            await pub.publish("lg/t", b"p%d" % i, qos=0)
            await sub.recv(timeout=10)
        assert await _await(
            lambda: app.ledger.totals().get("trunk_punt", 0) >= 6), (
            app.ledger.totals())
        ev = [e for e in app.ledger.recent(64)
              if e["reason"] == "trunk_punt"]
        assert ev, app.ledger.recent(64)
        assert sum(e["count"] for e in ev) >= 6
        assert app.metrics.val("messages.ledger.trunk_punt") >= 6
        # Python-plane reasons land in the same ledger
        app.ledger.record("device_failover", 1, detail="submit")
        api = ManagementApi(app)
        led = api.h_tracing_ledger({}, None)
        assert led["totals"]["trunk_punt"] >= 6
        assert led["totals"]["device_failover"] == 1
        assert any(e["reason"] == "trunk_punt" for e in led["events"])
        spans = api.h_tracing_spans({}, None)
        assert isinstance(spans, list)
        await sub.close(); await pub.close()

    run(main())
    server.stop()


# -- native-mode traces -------------------------------------------------------


def test_native_mode_clientid_trace_samples_without_punting():
    """mode="native" clientid traces keep the conn on the fast path
    (punts_trace stays 0 — the observed workload is NOT turned off)
    and the trace log receives the sampled publishes' SPAN timelines;
    mode="punt" keeps the round-8 full-fidelity behaviour."""
    app = BrokerApp()
    server = NativeBrokerServer(port=0, app=app, trace_sample_shift=0)
    server.start()
    app.trace.start("nt", "clientid", "nm-p", mode="native")

    async def main():
        sub = MqttClient(port=server.port, clientid="nm-s")
        await sub.connect()
        await sub.subscribe("nm/t", qos=0)
        pub = MqttClient(port=server.port, clientid="nm-p")
        await pub.connect()
        await _warm(pub, sub, "nm/t")
        for i in range(4):
            await pub.publish("nm/t", b"m%d" % i, qos=0)
            await sub.recv(timeout=10)
        st = server.fast_stats()
        assert st["punts_trace"] == 0, st       # never punted
        assert st["fast_in"] >= 4, st           # stayed native
        assert await _await(lambda: any(
            "[SPAN]" in ln and "ingress" in ln
            for ln in app.trace.log_lines("nt"))), (
            app.trace.log_lines("nt")[-5:])
        assert any("deliver_write" in ln
                   for ln in app.trace.log_lines("nt"))
        await sub.close(); await pub.close()

    run(main())
    server.stop()


# -- durable store ------------------------------------------------------------


def test_store_persists_trace_ids_across_restart(tmp_path):
    """The MSG-BATCH trace extension survives the disk roundtrip AND
    recovery: fetch returns the id before and after a reopen."""
    d = str(tmp_path / "ts")
    s = native.NativeStore(d, segment_bytes=64 * 1024, fsync="batch")
    tok = s.register("sid")
    g1 = s.append(1, 1, [tok], "t/a", b"traced", trace=0xDEADBEEF)
    g2 = s.append(1, 1, [tok], "t/b", b"plain")
    rows = s.fetch(tok)
    assert [(r[0], r[7]) for r in rows] == [(g1, 0xDEADBEEF), (g2, 0)]
    s.close()
    s2 = native.NativeStore(d, segment_bytes=64 * 1024, fsync="batch")
    rows = s2.fetch(s2.register("sid"))
    assert [(r[0], r[7]) for r in rows] == [(g1, 0xDEADBEEF), (g2, 0)]
    s2.close()


def test_durable_replay_rejoins_trace():
    """A sampled publish persisted for an OFFLINE persistent session
    carries its trace id into the store (store_append span at write
    time) and the clean_start=false resume replay re-joins the same
    timeline with a replay span."""
    app = BrokerApp(persistent_store=MemStore())
    server = NativeBrokerServer(port=0, app=app, trace_sample_shift=0)
    server.start()

    async def main():
        ps = MqttClient(port=server.port, clientid="dr-ps",
                        clean_start=False, proto_ver=5,
                        properties={"Session-Expiry-Interval": 300})
        await ps.connect()
        await ps.subscribe("dr/t", qos=1)
        fs = MqttClient(port=server.port, clientid="dr-fs")
        await fs.connect()
        await fs.subscribe("dr/t", qos=0)
        pub = MqttClient(port=server.port, clientid="dr-p")
        await pub.connect()
        await pub.publish("dr/t", b"warm", qos=1)
        await fs.recv(timeout=10)
        await ps.recv(timeout=10)
        await asyncio.sleep(0.6)
        await ps.close()                    # session survives offline
        await asyncio.sleep(0.2)
        n0 = len(server.spans)
        await pub.publish("dr/t", b"offline", qos=1)
        await fs.recv(timeout=10)
        assert await _await(lambda: len(server.spans) > n0)
        tid = next(t for t, s in server.spans.recent(4)
                   if "store_append" in [x[1] for x in s])
        stages = server.spans.stages(tid)
        assert stages[0] == "ingress"
        assert "store_append" in stages and "route" in stages
        # resume: the replay re-joins the SAME trace id
        ps2 = MqttClient(port=server.port, clientid="dr-ps",
                         clean_start=False, proto_ver=5,
                         properties={"Session-Expiry-Interval": 300})
        await ps2.connect()
        m = await ps2.recv(timeout=10)
        assert m.payload == b"offline"
        assert await _await(
            lambda: "replay" in server.spans.stages(tid)), (
            server.spans.trace(tid))
        await ps2.close(); await fs.close(); await pub.close()

    run(main())
    server.stop()


# -- escape hatches -----------------------------------------------------------


def test_wide_fanout_span_cap_sets_truncation_marker():
    """Round-17 satellite: the 8-per-publish deliver_write span cap
    used to clip a wide fan-out SILENTLY — a stitched timeline of a
    12-subscriber publish read as an 8-subscriber audience. Now the
    first clipped delivery emits ONE extra deliver_write span with aux
    bit 63 (host.cc kSpanTruncBit), and spans_recent surfaces it as
    truncated=True with the bit masked out of aux."""
    app = BrokerApp()
    server = NativeBrokerServer(port=0, app=app, trace_sample_shift=0)
    server.start()
    n_subs = 12

    async def main():
        subs = []
        for i in range(n_subs):
            s = MqttClient(port=server.port, clientid=f"tr-s{i}")
            await s.connect()
            await s.subscribe("tr/t", qos=0)
            subs.append(s)
        pub = MqttClient(port=server.port, clientid="tr-p")
        await pub.connect()
        await _warm(pub, subs[0], "tr/t")
        await pub.publish("tr/t", b"wide", qos=0)
        for s in subs:
            await s.recv(timeout=10)

        def widest():
            for tid, spans in server.spans.recent(8):
                dw = [s for s in spans if s[1] == "deliver_write"]
                if len(dw) == 9:
                    return tid, dw
            return None
        assert await _await(lambda: widest() is not None)
        _tid, dw = widest()
        # exactly the 8 capped spans plus ONE truncation marker
        marked = [s for s in dw if s[4] >> 63]
        clean = [s for s in dw if not s[4] >> 63]
        assert len(clean) == 8 and len(marked) == 1, dw
        # the marker's aux (bit 63 masked) is still a real conn id
        assert (marked[0][4] & ~(1 << 63)) in set(
            server._fast_conn_of.values())
        # the mgmt surface says so, with aux cleaned
        rec = server.spans_recent(8)
        tr = [sp for r in rec for sp in r["spans"]
              if sp["stage"] == "deliver_write" and sp["truncated"]]
        assert len(tr) == 1 and tr[0]["aux"] < (1 << 63), rec
        # an EXACTLY-at-cap fan-out stays unmarked: only the 9th
        # delivery mints the marker, the 8th is not a false positive
        for i in range(8, n_subs):
            await subs[i].unsubscribe("tr/t")
        await asyncio.sleep(0.3)
        await pub.publish("tr/t", b"exact", qos=0)
        for s in subs[:8]:
            await s.recv(timeout=10)

        def exact8():
            for tid, spans in server.spans.recent(8):
                dw = [s for s in spans if s[1] == "deliver_write"]
                if len(dw) == 8:
                    return dw
            return None
        assert await _await(lambda: exact8() is not None)
        assert all(not (s[4] >> 63) for s in exact8()), exact8()
        await pub.close()
        for s in subs:
            await s.close()

    run(main())
    server.stop()


def test_tracing_escape_hatch():
    """tracing=False: the sampler never ticks a trace — zero spans,
    zero traced publishes, plane stays fast; telemetry histograms keep
    working (tracing is its own switch under the telemetry hatch)."""
    server = NativeBrokerServer(port=0, app=BrokerApp(), tracing=False)
    server.start()

    async def main():
        sub = MqttClient(port=server.port, clientid="eh-s")
        await sub.connect()
        await sub.subscribe("eh/t", qos=0)
        pub = MqttClient(port=server.port, clientid="eh-p")
        await pub.connect()
        await _warm(pub, sub, "eh/t")
        for i in range(8):
            await pub.publish("eh/t", b"m%d" % i, qos=0)
            await sub.recv(timeout=10)
        await asyncio.sleep(0.4)
        st = server.fast_stats()
        assert st["fast_in"] >= 8, st
        assert st["traced_pubs"] == 0, st
        assert st["span_batches"] == 0, st
        assert len(server.spans) == 0
        await sub.close(); await pub.close()

    run(main())
    server.stop()


# -- qos1 replay shadow at the negotiated wire version (round 14) -------------


def _hello_sink(answer_hello: bool):
    """A test-controlled trunk endpoint: accepts one link, reads trunk
    records, answers HELLO at wire v1 when asked to, and NEVER acks a
    batch — so the dialer's qos1 replay ring provably holds every
    flushed batch when the link dies."""
    import socket
    import struct
    import threading

    srv = socket.socket()
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    state = {"conns": [], "srv": srv, "port": srv.getsockname()[1]}

    def loop():
        try:
            c, _ = srv.accept()
        except OSError:
            return
        state["conns"].append(c)
        c.settimeout(0.2)
        buf = b""
        while True:
            try:
                chunk = c.recv(65536)
            except socket.timeout:
                continue
            except OSError:
                return
            if not chunk:
                return
            buf += chunk
            while len(buf) >= 5:
                (ln,) = struct.unpack_from("<I", buf, 0)
                if len(buf) < 4 + ln:
                    break
                rtype = buf[4]
                buf = buf[4 + ln:]
                if rtype == 4 and answer_hello:
                    try:    # HELLO answer: this sink speaks wire v1
                        c.sendall(struct.pack("<IB", 2, 4) + bytes([1]))
                    except OSError:
                        return
                # type 2 (BATCH) is read and dropped: never acked

    t = threading.Thread(target=loop, daemon=True)
    t.start()
    return state


def _trunk_replay_pair(suffix: str):
    """Two apps + native servers with every publish sampled, A's
    forward_fn wired as the Python oracle lane into B (the
    test_native_trunk kill-replay fixture + tracing)."""
    app_a, app_b = BrokerApp(), BrokerApp()
    app_a.broker.node = f"nA{suffix}"
    app_b.broker.node = f"nB{suffix}"
    srv_a = NativeBrokerServer(port=0, app=app_a, trunk_port=0,
                               trace_sample_shift=0)
    srv_b = NativeBrokerServer(port=0, app=app_b, trunk_port=0,
                               trace_sample_shift=0)

    def forward(dest, filt, msg):
        deliveries = {}
        app_b.broker._dispatch_local(filt, msg, deliveries)
        app_b.cm.dispatch(deliveries)
    app_a.broker.forward_fn = forward
    srv_a.start()
    srv_b.start()
    return app_a, app_b, srv_a, srv_b


async def _replay_phase1(app_a, srv_a, srv_b, sink, topic, n=6):
    """Connect sub(B)/pub(A), earn the permit, trunk ``n`` sampled
    qos1 publishes into the never-acking sink, and return (sub, pub,
    payloads, flushed trace ids)."""
    sub = MqttClient(port=srv_b.port, clientid="rp-s")
    await sub.connect()
    await sub.subscribe(topic, qos=1)
    pub = MqttClient(port=srv_a.port, clientid="rp-p")
    await pub.connect()
    app_a.broker.router.add_route(topic, "nodeB")
    srv_a.trunk_register("nodeB", "127.0.0.1", sink["port"])
    assert await _await(
        lambda: srv_a.trunk_peer_status().get("nodeB"), timeout=8)
    await _warm(pub, sub, topic, qos=1)
    payloads = [b"r%03d" % i for i in range(n)]
    for p in payloads:
        await pub.publish(topic, p, qos=1)
        await asyncio.sleep(0.05)   # one poll cycle per publish: the
        #                             per-cycle sampler cap never clips
    assert await _await(
        lambda: srv_a.fast_stats()["trunk_out"] >= n), srv_a.fast_stats()
    flushed = [t for t, s in srv_a.spans.recent(64)
               if "trunk_flush" in [x[1] for x in s]]
    assert len(flushed) >= n - 1, (flushed, srv_a.spans.recent(64))
    return sub, pub, payloads, flushed


def test_trunk_replay_preserves_trace_ids_on_v1_links():
    """ROADMAP carried edge closed: the qos1 replay shadow is built at
    the link's negotiated wire version. Kill a link whose unacked ring
    holds SAMPLED qos1 batches, reconnect to a real v1 peer — the
    replayed batches keep their trace annotation: B's collector
    re-joins the SAME trace ids (trunk_recv + deliver_write) and every
    payload arrives."""
    app_a, app_b, srv_a, srv_b = _trunk_replay_pair("rv1")
    sink = _hello_sink(answer_hello=True)
    try:
        async def main():
            sub, pub, payloads, flushed = await _replay_phase1(
                app_a, srv_a, srv_b, sink, "rp/x")
            # kill the link: the ring keeps the (traced) replay shadow
            for c in sink["conns"]:
                c.close()
            sink["srv"].close()
            assert await _await(
                lambda: not srv_a.trunk_peer_status().get("nodeB"))
            # reconnect to B's REAL trunk (wire v1): replay at v1
            srv_a.trunk_register("nodeB", "127.0.0.1", srv_b.trunk_port)
            assert await _await(
                lambda: srv_a.fast_stats()["trunk_replays"] >= 1,
                timeout=10), srv_a.fast_stats()
            got = []
            while len(got) < len(payloads):
                m = await sub.recv(timeout=8)
                got.append(m.payload)
            assert sorted(got) == sorted(payloads), got
            # the SAME ids A flushed re-join on B — the replayed batch
            # kept its trace annotation across the kill
            assert await _await(
                lambda: any("trunk_recv" in srv_b.spans.stages(t)
                            for t in flushed)), srv_b.spans.recent(16)
            rejoined = [t for t in flushed
                        if "trunk_recv" in srv_b.spans.stages(t)
                        and "deliver_write" in srv_b.spans.stages(t)]
            assert len(rejoined) >= len(payloads) - 1, (
                flushed, srv_b.spans.recent(16))
            await sub.close(); await pub.close()

        run(main())
    finally:
        srv_a.stop(); srv_b.stop()


def test_trunk_replay_strips_trace_ids_for_v0_peers():
    """The symmetric safety edge: a replay shadow built on a v1 link
    that reconnects to a v0 peer is re-encoded at v0 (StripTraceRecord)
    — every payload still arrives (lossless strip) and the v0 peer
    never sees a trace id."""
    app_a, app_b, srv_a, srv_b = _trunk_replay_pair("rv0")
    sink = _hello_sink(answer_hello=True)
    try:
        async def main():
            sub, pub, payloads, flushed = await _replay_phase1(
                app_a, srv_a, srv_b, sink, "rq/x")
            for c in sink["conns"]:
                c.close()
            sink["srv"].close()
            assert await _await(
                lambda: not srv_a.trunk_peer_status().get("nodeB"))
            # B becomes an old peer BEFORE the link re-negotiates: it
            # never answers HELLO, so A completes the link at v0 after
            # the bounded grace and strips the replay shadow
            for h in srv_b.hosts:
                h.set_trunk_wire(0)
            srv_a.trunk_register("nodeB", "127.0.0.1", srv_b.trunk_port)
            assert await _await(
                lambda: srv_a.fast_stats()["trunk_replays"] >= 1,
                timeout=10), srv_a.fast_stats()
            got = []
            while len(got) < len(payloads):
                m = await sub.recv(timeout=8)
                got.append(m.payload)
            assert sorted(got) == sorted(payloads), got   # lossless
            await asyncio.sleep(0.4)
            for t in flushed:   # ...but no id ever reached B
                assert srv_b.spans.trace(t) == [], srv_b.spans.recent(16)
            await sub.close(); await pub.close()

        run(main())
    finally:
        srv_a.stop(); srv_b.stop()
