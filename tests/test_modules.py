"""emqx_modules-family services: rewrite, topic metrics, telemetry,
auto-subscribe, PSK, statsd (reference suites: emqx_rewrite_SUITE,
emqx_topic_metrics_SUITE, emqx_telemetry_SUITE, emqx_auto_subscribe_SUITE,
emqx_psk_SUITE, emqx_statsd_SUITE)."""

import pytest

from emqx_tpu.access.psk import PskStore
from emqx_tpu.app import BrokerApp
from emqx_tpu.broker.channel import Channel
from emqx_tpu.core.message import Message
from emqx_tpu.mqtt import packet as P
from emqx_tpu.observe.statsd import StatsdPusher, render_lines
from emqx_tpu.services.rewrite import TopicRewrite
from emqx_tpu.services.telemetry import Telemetry
from emqx_tpu.services.topic_metrics import TopicMetrics


def _connect(app, cid, username=None):
    ch = Channel(app.broker, app.cm)
    ch.handle_in(P.Connect(proto_ver=P.MQTT_V5, clientid=cid,
                           username=username))
    return ch


# -- rewrite -------------------------------------------------------------------

def test_rewrite_publish_with_captures_and_binds():
    rw = TopicRewrite()
    rw.add_rule("publish", "x/#", r"^x/y/(.+)$", "z/y/$1/%c")
    app = BrokerApp()
    rw.attach(app.hooks)
    got = []
    app.hooks.add("message.publish", lambda m: got.append(m.topic) or None,
                  priority=500)     # after rewrite (1000), before routing
    app.broker.publish(Message(topic="x/y/2", from_="dev1"))
    assert got == ["z/y/2/dev1"]
    # filter hit, regex miss → unchanged
    got.clear()
    app.broker.publish(Message(topic="x/other", from_="dev1"))
    assert got == ["x/other"]


def test_rewrite_subscribe_end_to_end():
    app = BrokerApp()
    app.rewrite.add_rule("subscribe", "y/+/z/#", r"^y/(.+)/z/(.+)$",
                         "y/z/$2")
    ch = _connect(app, "c1")
    ch.handle_in(P.Subscribe(packet_id=1,
                             topic_filters=[("y/a/z/b", {"qos": 1})]))
    # the stored subscription is the REWRITTEN filter
    assert ("c1", "y/z/b") in app.broker.suboption
    # delivery flows through the rewritten filter
    sent = []
    ch._send = sent.extend
    app.cm.dispatch(app.broker.publish(Message(topic="y/z/b", payload=b"m")))
    assert any(getattr(p, "topic", None) == "y/z/b" for p in sent)
    # unsubscribe applies the same rewrite
    ch.handle_in(P.Unsubscribe(packet_id=2, topic_filters=["y/a/z/b"]))
    assert ("c1", "y/z/b") not in app.broker.suboption


# -- topic metrics -------------------------------------------------------------

def test_topic_metrics_counts_in_out():
    app = BrokerApp()
    app.topic_metrics.register("room/+/temp")
    with pytest.raises(ValueError):
        app.topic_metrics.register("bad/#/filter")
    sub = _connect(app, "tm-sub")
    sub.handle_in(P.Subscribe(packet_id=1,
                              topic_filters=[("room/#", {"qos": 0})]))
    pub = _connect(app, "tm-pub")
    pub.handle_in(P.Publish(topic="room/7/temp", payload=b"20", qos=1,
                            packet_id=1))
    pub.handle_in(P.Publish(topic="hall/temp", payload=b"20", qos=0))
    m = app.topic_metrics.metrics("room/+/temp")
    assert m["messages.in"] == 1 and m["messages.qos1.in"] == 1
    assert m["messages.out"] == 1          # delivered to tm-sub
    assert app.topic_metrics.deregister("room/+/temp")


# -- telemetry -----------------------------------------------------------------

def test_telemetry_report_and_schedule():
    app = BrokerApp()
    _connect(app, "t-c1")
    sent = []
    tel = Telemetry(app, enable=True, send_fn=sent.append)
    report = tel.build_report()
    assert report["num_clients"] == 1 and "uuid" in report
    assert tel.tick(now=1e9) and sent        # first due immediately
    assert not tel.tick(now=1e9 + 60)        # not due again for a week
    tel.enable = False
    assert not tel.tick(now=2e9)


# -- auto subscribe ------------------------------------------------------------

def test_auto_subscribe_on_connect_with_placeholders():
    app = BrokerApp()
    app.auto_subscribe.add("devices/%c/cmd", qos=1)
    app.auto_subscribe.add("users/%u/inbox")
    ch = _connect(app, "dev-7", username="alice")
    assert ("dev-7", "devices/dev-7/cmd") in app.broker.suboption
    assert ("dev-7", "users/alice/inbox") in app.broker.suboption
    # session is coherent → delivery works
    sent = []
    ch._send = sent.extend
    app.cm.dispatch(app.broker.publish(
        Message(topic="devices/dev-7/cmd", payload=b"reboot")))
    assert any(getattr(p, "topic", None) == "devices/dev-7/cmd"
               for p in sent)


# -- psk -----------------------------------------------------------------------

def test_psk_store_import_and_lookup(tmp_path):
    f = tmp_path / "psk.txt"
    f.write_text("# fixtures\nclient1:AABBCC\nclient2:00112233\nbadline\n")
    store = PskStore(init_file=str(f))
    assert len(store) == 2
    assert store.lookup("client1") == bytes.fromhex("AABBCC")
    assert store.lookup("nope") is None
    store.enable = False
    assert store.lookup("client1") is None   # disabled → reject handshakes
    store.enable = True
    assert store.delete("client1") and store.lookup("client1") is None


# -- statsd --------------------------------------------------------------------

def test_statsd_lines_and_flush():
    app = BrokerApp()
    app.metrics.inc("messages.received", 5)
    datagrams = []
    pusher = StatsdPusher(app, enable=True, flush_interval_s=10,
                          send_fn=datagrams.append)
    assert pusher.tick(now=100.0)
    assert not pusher.tick(now=105.0)        # inside interval
    assert pusher.tick(now=111.0)
    text = b"\n".join(datagrams).decode()
    assert "emqx.messages.received:5|g" in text
    lines = render_lines(app.metrics, app.stats)
    assert all(l.endswith("|g") for l in lines)
