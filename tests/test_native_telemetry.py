"""Native telemetry plane (round 8): in-host latency histograms,
per-poll kind-8 snapshot export, and the fast-path flight recorder.

The C++ host (native/src/host.cc) bumps fixed 64-bucket log-scale
histograms on the poll thread and ships per-cycle DELTAS as batched
kind-8 records (chunked at the tap bound like kinds 6/7);
broker/native_server.py folds them into histogram-aware Metrics
(observe/metrics.py), prometheus (_bucket/_sum/_count), $SYS latency
heartbeats, and slow_subs (native ack RTT). TraceManager clientid
traces punt their conns at the C++ seam (emqx_host_set_trace) so a
trace captures publishes from a connection that was on the native fast
path — the ISSUE 3 acceptance shape. Reference anchors: HdrHistogram
(log-bucketed capture), Dapper (always-on low-overhead recording),
emqx_slow_subs.erl (ack-latency ranking)."""

import asyncio
import socket
import struct
import time

import pytest

from emqx_tpu import native

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native lib unavailable")

from emqx_tpu.app import BrokerApp            # noqa: E402
from emqx_tpu.broker.native_server import NativeBrokerServer  # noqa: E402
from emqx_tpu.mqtt.client import MqttClient   # noqa: E402
from emqx_tpu.observe.metrics import (        # noqa: E402
    HIST_EDGES_NS, LatencyHistogram, hist_bucket,
)


def run(coro):
    asyncio.run(coro)


async def _settle(seconds=0.4):
    await asyncio.sleep(seconds)


def _connect_frame(cid: bytes) -> bytes:
    vh = (b"\x00\x04MQTT\x04\x02\x00\x3c"
          + struct.pack(">H", len(cid)) + cid)
    return bytes([0x10, len(vh)]) + vh


def _publish_frame(topic: bytes, payload: bytes) -> bytes:
    vh = struct.pack(">H", len(topic)) + topic + payload
    assert len(vh) < 128
    return bytes([0x30, len(vh)]) + vh


# -- bucket math (python mirror of host.cc HistBucket) -----------------------

def test_hist_edges_and_bucket_mirror_invariants():
    assert len(HIST_EDGES_NS) == 64
    prev = 0.0
    for e in HIST_EDGES_NS:
        assert e > prev
        prev = e
    assert HIST_EDGES_NS[-1] == float("inf")
    # every value lands in the bucket whose [lower, upper) contains it
    for ns in list(range(0, 300)) + [1000, 4095, 123456, 10**6, 10**9,
                                     2**31, 2**32 - 1, 2**32, 2**40]:
        b = hist_bucket(ns)
        lo = HIST_EDGES_NS[b - 1] if b else 0.0
        hi = HIST_EDGES_NS[b]
        assert lo <= ns < hi, (ns, b, lo, hi)
    # ~power-of-√2 spacing: consecutive finite edges within [1.3, 1.6]x
    for i in range(1, 62):
        r = HIST_EDGES_NS[i + 1] / HIST_EDGES_NS[i]
        assert 1.3 < r < 1.6, (i, r)


def test_latency_histogram_percentiles_and_delta_fold():
    h = LatencyHistogram()
    for v in (100, 200, 400, 800, 100_000):
        h.observe(v)
    assert h.count == 5 and h.sum_ns == 101_500
    p50, p99 = h.percentile(0.5), h.percentile(0.99)
    assert 200 <= p50 <= 500 and p99 >= 50_000
    assert p50 <= p99 <= h.percentile(0.999)
    # folding deltas reproduces an identical histogram
    h2 = LatencyHistogram()
    h2.observe_delta(h.count, h.sum_ns,
                     {i: int(h.counts[i]) for i in range(64)
                      if h.counts[i]})
    assert (h2.counts == h.counts).all()
    assert h2.summary() == h.summary()


# -- end-to-end: stage histograms populate and export ------------------------

def test_stage_histograms_populate_and_render():
    """QoS1 traffic on the fast path fills ingress_route (sampled
    1-in-8, deterministically), route_flush, qos1_rtt (every
    windowed delivery while a sample slot is free), and gil_stint —
    and the whole set renders in prometheus + the $SYS latency
    heartbeat."""
    app = BrokerApp()
    server = NativeBrokerServer(port=0, app=app)
    server.start()

    async def main():
        sub = MqttClient(port=server.port, clientid="hs")
        await sub.connect()
        await sub.subscribe("h/x", qos=1)
        pub = MqttClient(port=server.port, clientid="hp")
        await pub.connect()
        await pub.publish("h/x", b"warm", qos=1)   # slow path, permit
        await sub.recv(timeout=10)
        await _settle(0.6)
        for i in range(40):
            await pub.publish("h/x", b"m%d" % i, qos=1)
            await sub.recv(timeout=10)
        await _settle(0.6)
        summ = server.latency_summary()
        # the global 1-in-8 ticker saw 41 PUBLISH ticks (warm + 40
        # fast); samples land on ticks 8..40 — all on the walk path
        assert summ["ingress_route"]["count"] == 5, summ
        assert summ["route_flush"]["count"] >= 1, summ
        assert summ["qos1_rtt"]["count"] == 40, summ
        assert summ["gil_stint"]["count"] > 0, summ
        for stage in ("ingress_route", "qos1_rtt"):
            s = summ[stage]
            assert 0 < s["p50_us"] <= s["p99_us"] <= s["p999_us"], s
        # histogram-aware Metrics: the same objects live on the node
        # metrics under latency.native.<stage>
        h = app.metrics.hist("latency.native.qos1_rtt")
        assert h is not None and h.count == 40
        prom = app.prometheus()
        for stage in ("ingress_route", "route_flush", "qos1_rtt",
                      "gil_stint"):
            base = f"emqx_latency_native_{stage}_seconds"
            assert f"{base}_bucket" in prom, stage
            assert f"{base}_sum" in prom and f"{base}_count" in prom
        assert 'le="+Inf"' in prom
        # $SYS latency heartbeat
        got = []
        app.sys.publish_fn = got.append
        app.sys.publish_latency()
        topics = {m.topic for m in got}
        node = app.broker.node
        for q in ("p50", "p99", "p999", "count"):
            t = f"$SYS/brokers/{node}/latency/native/qos1_rtt/{q}"
            assert t in topics, sorted(topics)
        await sub.close(); await pub.close()

    run(main())
    server.stop()


# -- kind-8 chunking + delta totals (satellite: snapshot-under-load) ---------

def test_kind8_chunks_at_tap_bound_and_records_survive():
    """A cycle whose telemetry exceeds the tap bound must CHUNK into
    several kind-8 events with no sub-record split or lost (mirror of
    the kind-7 chunking regression): 60 flight-recorder dumps forced
    in ONE ApplyPending far exceed the small cap."""
    host = native.NativeHost(port=0, max_size=2048)  # cap = 1025
    socks, conns = [], []
    try:
        for i in range(60):
            s = socket.create_connection(("127.0.0.1", host.port))
            s.sendall(_connect_frame(b"c%03d" % i))
            socks.append(s)
        deadline = time.time() + 10
        frames = 0
        while (len(conns) < 60 or frames < 60) and time.time() < deadline:
            for kind, conn, payload in host.poll(20):
                if kind == native.EV_OPEN:
                    conns.append(conn)
                elif kind == native.EV_FRAME:
                    frames += 1
        assert len(conns) == 60 and frames == 60
        # 60 trace attaches queue as ops and apply in ONE poll cycle:
        # each dumps its recorder (open + frame = 2 entries, ~43B), so
        # the cycle writes ~2.6KB against a ~1KB cap
        for c in conns:
            host.set_trace(c, True)
        tele_events, flights = [], []
        deadline = time.time() + 10
        while len(flights) < 60 and time.time() < deadline:
            for kind, conn, payload in host.poll(20):
                if kind == native.EV_TELEMETRY:
                    tele_events.append(payload)
                    for rec in native.parse_telemetry(payload):
                        if rec[0] == "flight":
                            flights.append(rec)
        assert len(flights) == 60, len(flights)
        assert len(tele_events) >= 3, (
            "expected the cycle to chunk at the tap bound",
            len(tele_events))
        for _, conn_id, reason, entries in flights:
            assert conn_id in conns
            assert reason == 3                      # trace attach
            assert len(entries) == 2, entries       # open + connect
            assert entries[0][1] == 1               # fr open
            assert entries[1][1] == 2               # slow-plane frame
            assert entries[1][2] == 1               # CONNECT ptype
    finally:
        for s in socks:
            try:
                s.close()
            except OSError:
                pass
        for _ in range(5):
            list(host.poll(10))
        host.destroy()


def test_kind8_histogram_deltas_sum_to_totals_across_cycles():
    """Per-cycle histogram deltas folded across MANY cycles (and any
    chunk boundaries) must reproduce the exact totals: 80 fast-path
    publishes sample exactly 10 ingress_route observations (global
    1-in-8 ticker), and the bucket deltas sum to the count deltas."""
    host = native.NativeHost(port=0, max_size=2048)
    try:
        pub = socket.create_connection(("127.0.0.1", host.port))
        sub = socket.create_connection(("127.0.0.1", host.port))
        ids = []
        deadline = time.time() + 10
        while len(ids) < 2 and time.time() < deadline:
            for kind, conn, payload in host.poll(20):
                if kind == native.EV_OPEN:
                    ids.append(conn)
        pub_id, sub_id = ids
        host.enable_fast(pub_id, 4)
        host.sub_add(sub_id, "t")
        host.permit(pub_id, "t")
        list(host.poll(20))                        # apply the ops
        by_stage = {}                              # stage -> [cnt, sum, {b: d}]
        fast_in0 = host.stats()["fast_in"]
        for burst in range(8):                     # 8 bursts x 10 msgs
            for i in range(10):
                pub.sendall(_publish_frame(b"t", b"p%02d" % i))
            # drain a few cycles so each burst's deltas flush separately
            t0 = time.time()
            while time.time() - t0 < 0.25:
                for kind, conn, payload in host.poll(10):
                    if kind != native.EV_TELEMETRY:
                        continue
                    for rec in native.parse_telemetry(payload):
                        if rec[0] != "hist":
                            continue
                        _, stage, cnt, sum_ns, buckets = rec
                        acc = by_stage.setdefault(stage, [0, 0, {}])
                        acc[0] += cnt
                        acc[1] += sum_ns
                        for b, d in buckets.items():
                            acc[2][b] = acc[2].get(b, 0) + d
                if host.stats()["fast_in"] - fast_in0 >= (burst + 1) * 10:
                    break
        assert host.stats()["fast_in"] - fast_in0 == 80
        # final drain: hist deltas flush on a ~100ms cadence, so the
        # last burst's samples may still be pending
        t0 = time.time()
        while time.time() - t0 < 1.0:
            for kind, conn, payload in host.poll(25):
                if kind != native.EV_TELEMETRY:
                    continue
                for rec in native.parse_telemetry(payload):
                    if rec[0] != "hist":
                        continue
                    _, stage, cnt, sum_ns, buckets = rec
                    acc = by_stage.setdefault(stage, [0, 0, {}])
                    acc[0] += cnt
                    acc[1] += sum_ns
                    for b, d in buckets.items():
                        acc[2][b] = acc[2].get(b, 0) + d
            if by_stage.get(0, [0])[0] >= 10:
                break
        ing = by_stage.get(0)                      # kHistIngressRoute
        assert ing is not None, by_stage.keys()
        cnt, sum_ns, buckets = ing
        assert cnt == 10, ing                      # 80 publishes / 8
        assert sum(buckets.values()) == cnt        # deltas sum to totals
        assert sum_ns > 0
        # gil_stint flushed every cycle: its bucket deltas must also
        # reconcile with its count across all those records
        gil = by_stage.get(5)                      # kHistGilStint
        assert gil is not None and sum(gil[2].values()) == gil[0] > 0
        pub.close(); sub.close()
    finally:
        for _ in range(5):
            list(host.poll(10))
        host.destroy()


# -- trace punt (the ISSUE 3 acceptance criterion) ---------------------------

def test_clientid_trace_captures_fast_path_publishes():
    """A clientid trace started via TraceManager on a publisher already
    riding the native fast path must capture its subsequent publishes
    (full hook visibility via the C++ trace punt) AND receive the
    connection's flight-recorder tail."""
    app = BrokerApp()
    server = NativeBrokerServer(port=0, app=app)
    server.start()

    async def main():
        sub = MqttClient(port=server.port, clientid="zs")
        await sub.connect()
        await sub.subscribe("z/x", qos=0)
        pub = MqttClient(port=server.port, clientid="zp")
        await pub.connect()
        await pub.publish("z/x", b"warm", qos=0)
        await sub.recv(timeout=10)
        await _settle(0.6)
        for i in range(5):
            await pub.publish("z/x", b"fast%d" % i, qos=0)
            await sub.recv(timeout=10)
        assert server.fast_stats()["fast_in"] >= 5   # provably fast
        app.trace.start("t-accept", "clientid", "zp")
        await _settle(0.5)
        for i in range(3):
            await pub.publish("z/x", b"traced%d" % i, qos=0)
            m = await sub.recv(timeout=10)           # still delivered
            assert m.payload == b"traced%d" % i
        await _settle(0.5)
        st = server.fast_stats()
        assert st["punts_trace"] >= 3, st
        assert st["fr_dumps"] >= 1, st
        lines = app.trace.log_lines("t-accept")
        pubs = [ln for ln in lines if "PUBLISH" in ln and "z/x" in ln]
        assert len(pubs) >= 3, lines
        flights = [ln for ln in lines if "FLIGHT" in ln]
        assert flights and "fast_pub" in flights[0], lines
        # stopping the trace un-punts AND flushes permits: the first
        # publish re-earns the grant on the slow path, the next one
        # rides the fast plane again
        app.trace.stop("t-accept")
        await _settle(0.6)
        before = server.fast_stats()["fast_in"]
        await pub.publish("z/x", b"re-earn", qos=0)
        await sub.recv(timeout=10)
        await _settle(0.8)
        await pub.publish("z/x", b"after", qos=0)
        await sub.recv(timeout=10)
        await _settle(0.5)
        assert server.fast_stats()["fast_in"] > before
        await sub.close(); await pub.close()

    run(main())
    server.stop()


def test_trace_started_before_connect_punts_from_first_frame():
    """A running clientid trace must catch a publisher that connects
    AFTER trace start — _maybe_enable_fast marks the conn at the C++
    seam immediately, so not even the first permitted publish is
    missed."""
    app = BrokerApp()
    server = NativeBrokerServer(port=0, app=app)
    server.start()
    app.trace.start("t-pre", "clientid", "late")

    async def main():
        sub = MqttClient(port=server.port, clientid="ps")
        await sub.connect()
        await sub.subscribe("p/x", qos=0)
        pub = MqttClient(port=server.port, clientid="late")
        await pub.connect()
        for i in range(4):
            await pub.publish("p/x", b"m%d" % i, qos=0)
            await sub.recv(timeout=10)
            await _settle(0.3)
        lines = app.trace.log_lines("t-pre")
        pubs = [ln for ln in lines if "PUBLISH" in ln and "p/x" in ln]
        assert len(pubs) == 4, lines            # every single message
        assert server.fast_stats()["fast_in"] == 0  # none went native
        await sub.close(); await pub.close()

    run(main())
    server.stop()


# -- flight recorder on protocol error ---------------------------------------

def test_flight_recorder_dumps_on_protocol_error():
    """A C++-level framing error (oversized remaining-length) tears the
    conn down AND surfaces its flight-recorder tail to Python."""
    server = NativeBrokerServer(port=0, app=BrokerApp(),
                                max_packet_size=4096)
    server.start()
    try:
        s = socket.create_connection(("127.0.0.1", server.port))
        s.sendall(_connect_frame(b"bad"))
        time.sleep(0.3)
        # remaining length ~268M >> max_packet_size: frame_error in C++
        s.sendall(bytes([0x30, 0xFF, 0xFF, 0xFF, 0x7F]))
        deadline = time.time() + 5
        while not server.flight_records and time.time() < deadline:
            time.sleep(0.05)
        assert server.flight_records, "no flight-recorder dump arrived"
        _conn, reason, entries = server.flight_records[-1]
        assert reason == 2                       # protocol_error
        events = [e[1] for e in entries]
        assert 1 in events and 2 in events       # open + the CONNECT
        assert server.fast_stats()["fr_dumps"] >= 1
        s.close()
    finally:
        server.stop()


def test_python_teardown_closes_without_fr_dump():
    """ISSUE 6 carried edge: a PYTHON-plane channel error tears the conn
    down through _drop -> emqx_host_close_conn, which the C++ side closes
    as closed_by_host — NO flight-recorder dump (Python-side teardown
    used to read as an abnormal close and dump on every raced
    sock_error).  A genuine C++-level framing error on the very same
    server still dumps, so the recorder stays a protocol-error signal."""
    server = NativeBrokerServer(port=0, app=BrokerApp(),
                                max_packet_size=4096)
    server.start()
    try:
        s = socket.create_connection(("127.0.0.1", server.port))
        s.sendall(_connect_frame(b"pyerr"))
        time.sleep(0.3)
        # frames cleanly in C++ (remaining length 2) but the topic
        # length claims 80 bytes: FrameError on the PYTHON plane ->
        # _drop(conn, "frame_error") -> closed_by_host in the host
        s.sendall(bytes([0x30, 0x02, 0x00, 0x50]))
        deadline = time.time() + 5
        while time.time() < deadline:
            try:
                if s.recv(4096) == b"":
                    break                        # host closed the socket
            except OSError:
                break
        time.sleep(0.3)
        assert server.fast_stats()["fr_dumps"] == 0, server.fast_stats()
        assert not server.flight_records
        s.close()
        # control arm: a framer-level error (oversized remaining length)
        # is a C++ protocol error and MUST dump
        s2 = socket.create_connection(("127.0.0.1", server.port))
        s2.sendall(_connect_frame(b"cpperr"))
        time.sleep(0.3)
        s2.sendall(bytes([0x30, 0xFF, 0xFF, 0xFF, 0x7F]))
        deadline = time.time() + 5
        while not server.flight_records and time.time() < deadline:
            time.sleep(0.05)
        assert server.fast_stats()["fr_dumps"] == 1, server.fast_stats()
        _conn, reason, _entries = server.flight_records[-1]
        assert reason == 2                       # protocol_error
        s2.close()
    finally:
        server.stop()


# -- slow_subs fed by native ack RTT -----------------------------------------

def _slow_ack_record(conn_id: int, rtt_us: int, qos: int,
                     topic: str) -> bytes:
    """One kind-8 sub-3 slow-ack sub-record, byte-for-byte what
    host.cc EmitSlowAck produces."""
    t = topic.encode()
    return (bytes([3]) + conn_id.to_bytes(8, "little")
            + rtt_us.to_bytes(4, "little") + bytes([qos])
            + len(t).to_bytes(2, "little") + t)


def test_native_ack_rtt_feeds_slow_subs():
    """slow_subs previously only saw the Python plane; native ack RTTs
    rank subscribers tagged plane='native'.

    Deflaked (round 13 satellite): the ranking assertions are driven by
    INJECTED RTTs through the same kind-8 slow-ack fold the C++ plane
    feeds (_on_telemetry), so the ordering/threshold checks never race
    wall-clock poll cadence; the live end-to-end emission is covered by
    a bounded deadline wait instead of fixed sleeps."""
    app = BrokerApp()
    app.slow_subs.threshold_ms = 0
    server = NativeBrokerServer(port=0, app=app)
    server.start()

    async def main():
        sub = MqttClient(port=server.port, clientid="slow-sub")
        await sub.connect()
        await sub.subscribe("s/x", qos=1)
        pub = MqttClient(port=server.port, clientid="slow-pub")
        await pub.connect()
        await pub.publish("s/x", b"warm", qos=1)
        await sub.recv(timeout=10)
        await _settle(0.6)
        # -- injected-RTT ranking (deterministic) -----------------------
        sub_conn = server._fast_conn_of.get("slow-sub")
        if sub_conn is None:   # subscriber conn id by table lookup
            sub_conn = next(c for c, nc in server.conns.items()
                            if nc.channel.clientid == "slow-sub")
        server._on_telemetry(
            _slow_ack_record(sub_conn, 7_000, 1, "s/x")
            + _slow_ack_record(sub_conn, 45_000, 1, "s/x"))
        entries = [e for e in app.slow_subs.top() if e.plane == "native"]
        assert entries, app.slow_subs.top()
        assert entries[0].clientid == "slow-sub"
        assert entries[0].topic == "s/x"
        assert entries[0].latency_ms == 45   # the worst injected RTT
        # -- live end-to-end emission (bounded deadline, no sleeps) -----
        app.slow_subs.clear()
        for i in range(5):
            await pub.publish("s/x", b"m%d" % i, qos=1)
            await sub.recv(timeout=10)
        deadline = time.monotonic() + 8.0
        live = []
        while time.monotonic() < deadline:
            live = [e for e in app.slow_subs.top()
                    if e.plane == "native"]
            if live:
                break
            await asyncio.sleep(0.05)
        assert live, "no native slow-ack sample surfaced within 8s"
        assert live[0].clientid == "slow-sub"
        await sub.close(); await pub.close()

    run(main())
    server.stop()


# -- escape hatch ------------------------------------------------------------

def test_telemetry_escape_hatch_disables_everything():
    """telemetry=False (the EMQX_NATIVE_TELEMETRY=0 hatch): no
    histograms, no kind-8 records, no flight recorders — the bench's
    observe_overhead section measures this exact toggle."""
    server = NativeBrokerServer(port=0, app=BrokerApp(), telemetry=False)
    server.start()

    async def main():
        sub = MqttClient(port=server.port, clientid="os")
        await sub.connect()
        await sub.subscribe("o/x", qos=1)
        pub = MqttClient(port=server.port, clientid="op")
        await pub.connect()
        await pub.publish("o/x", b"warm", qos=1)
        await sub.recv(timeout=10)
        await _settle(0.6)
        for i in range(10):
            await pub.publish("o/x", b"m%d" % i, qos=1)
            await sub.recv(timeout=10)
        await _settle(0.5)
        st = server.fast_stats()
        assert st["fast_in"] > 0, st             # plane still fast
        assert st["telemetry_batches"] == 0, st
        assert st["fr_dumps"] == 0, st
        assert server.latency_summary() == {}
        assert not server.flight_records
        await sub.close(); await pub.close()

    run(main())
    server.stop()


def test_telemetry_env_var_escape_hatch(monkeypatch):
    monkeypatch.setenv("EMQX_NATIVE_TELEMETRY", "0")
    server = NativeBrokerServer(port=0, app=BrokerApp())
    assert server.telemetry is False
    server.host.destroy()
    monkeypatch.setenv("EMQX_NATIVE_TELEMETRY", "1")
    server2 = NativeBrokerServer(port=0, app=BrokerApp())
    assert server2.telemetry is True
    server2.host.destroy()
