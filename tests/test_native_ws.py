"""The native (C++) WebSocket plane — RFC6455 in host.cc/ws.h driven
against broker/ws.py's codec as the conformance oracle: the test client
masks with the ORACLE's encoder and decodes server frames with the
ORACLE's decoder, so any disagreement between the two RFC6455
implementations fails here.

Covers: upgrade handshake (accept key, subprotocol echo, bad-path /
bad-header 400s), masked client frames (and the unmasked-client
rejection), MQTT packets split across WS frame boundaries and
fragmented data messages, ping/pong keepalive, close-code echo, QoS1
end-to-end over WS (native fast path engaged), WS/TCP interop on one
host, and the deployment fallback story (the asyncio plane serves what
the native listener rejects)."""

import base64
import os
import socket
import struct
import time

import pytest

from emqx_tpu import native
from emqx_tpu.broker.ws import (
    OP_BINARY, OP_CLOSE, OP_PING, OP_PONG, FrameDecoder, accept_key,
    encode_frame,
)
from emqx_tpu.mqtt import packet as P
from emqx_tpu.mqtt.frame import Parser, serialize

pytestmark = pytest.mark.skipif(
    not native.available(), reason=f"native lib: {native.build_error()}")


@pytest.fixture()
def server():
    from emqx_tpu.app import BrokerApp
    from emqx_tpu.broker.native_server import NativeBrokerServer

    srv = NativeBrokerServer(port=0, app=BrokerApp(), ws_port=0,
                             session_opts={"max_inflight": 64})
    srv.start()
    yield srv
    srv.stop()


class NativeWsClient:
    """Masked-frame WS client over a blocking socket (the native server
    runs on its own thread); codec = the asyncio oracle's."""

    def __init__(self, port: int, path: str = "/mqtt"):
        self.sock = socket.create_connection(("127.0.0.1", port))
        self.sock.settimeout(10)
        self.path = path
        self.dec = FrameDecoder(require_mask=False)  # server sends bare
        self.parser = Parser()
        self.inbox: list = []
        self.control: list = []

    def handshake(self) -> bytes:
        key = base64.b64encode(os.urandom(16)).decode()
        self.sock.sendall((
            f"GET {self.path} HTTP/1.1\r\nHost: localhost\r\n"
            "Upgrade: websocket\r\nConnection: Upgrade\r\n"
            f"Sec-WebSocket-Key: {key}\r\n"
            "Sec-WebSocket-Version: 13\r\n"
            "Sec-WebSocket-Protocol: mqtt\r\n\r\n").encode())
        resp = b""
        while b"\r\n\r\n" not in resp:
            chunk = self.sock.recv(4096)
            assert chunk, "server closed during handshake"
            resp += chunk
        head, rest = resp.split(b"\r\n\r\n", 1)
        assert b"101" in head.split(b"\r\n")[0], head
        assert accept_key(key).encode() in head, head
        assert b"Sec-WebSocket-Protocol: mqtt" in head, head
        if rest:
            self._ingest(rest)
        return head

    def _ingest(self, data: bytes) -> None:
        for op, payload in self.dec.feed(data):
            if op == OP_BINARY:
                self.inbox.extend(self.parser.feed(payload))
            else:
                self.control.append((op, payload))

    def send_mqtt(self, pkt, ver=P.MQTT_V4) -> None:
        self.sock.sendall(
            encode_frame(OP_BINARY, serialize(pkt, ver), mask=True))

    def send_frame(self, opcode: int, payload: bytes,
                   mask: bool = True) -> None:
        self.sock.sendall(encode_frame(opcode, payload, mask=mask))

    def recv_mqtt(self, timeout: float = 10.0):
        self.sock.settimeout(timeout)
        while not self.inbox:
            data = self.sock.recv(65536)
            assert data, "server closed"
            self._ingest(data)
        return self.inbox.pop(0)

    def recv_control(self, timeout: float = 10.0):
        self.sock.settimeout(timeout)
        while not self.control:
            data = self.sock.recv(65536)
            assert data, "server closed"
            self._ingest(data)
        return self.control.pop(0)

    def mqtt_connect(self, cid: str):
        self.send_mqtt(P.Connect(clientid=cid))
        ack = self.recv_mqtt()
        assert ack.reason_code == 0, ack
        return ack

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


# -- handshake -----------------------------------------------------------------

def test_handshake_accept_key_and_subprotocol(server):
    c = NativeWsClient(server.ws_port)
    c.handshake()        # asserts 101 + RFC6455 accept key + mqtt echo
    c.mqtt_connect("nws-hs")
    assert server.fast_stats()["ws_handshakes"] >= 1
    c.close()


def test_bad_path_and_bad_headers_rejected(server):
    # wrong request-target → 400 (the asyncio plane serves other paths)
    s = socket.create_connection(("127.0.0.1", server.ws_port))
    s.settimeout(10)
    s.sendall(b"GET /nope HTTP/1.1\r\nHost: x\r\n"
              b"Upgrade: websocket\r\nConnection: Upgrade\r\n"
              b"Sec-WebSocket-Key: AAAAAAAAAAAAAAAAAAAAAA==\r\n\r\n")
    assert b"400" in s.recv(4096)
    s.close()
    # missing Sec-WebSocket-Key → 400
    s = socket.create_connection(("127.0.0.1", server.ws_port))
    s.settimeout(10)
    s.sendall(b"GET /mqtt HTTP/1.1\r\nHost: x\r\n"
              b"Upgrade: websocket\r\nConnection: Upgrade\r\n\r\n")
    assert b"400" in s.recv(4096)
    s.close()
    # POST → 400
    s = socket.create_connection(("127.0.0.1", server.ws_port))
    s.settimeout(10)
    s.sendall(b"POST /mqtt HTTP/1.1\r\nHost: x\r\n"
              b"Upgrade: websocket\r\nConnection: Upgrade\r\n"
              b"Sec-WebSocket-Key: AAAAAAAAAAAAAAAAAAAAAA==\r\n\r\n")
    assert b"400" in s.recv(4096)
    s.close()
    assert server.fast_stats()["ws_rejects"] >= 3


def test_unmasked_client_frame_closes_1002(server):
    c = NativeWsClient(server.ws_port)
    c.handshake()
    c.send_frame(OP_BINARY, serialize(P.Connect(clientid="bare"),
                                      P.MQTT_V4), mask=False)
    op, payload = c.recv_control()
    assert op == OP_CLOSE
    assert struct.unpack(">H", payload[:2])[0] == 1002
    c.close()


# -- framing -------------------------------------------------------------------

def test_mqtt_packets_cross_ws_frame_boundaries(server):
    """One WS frame may carry several MQTT packets, and one MQTT packet
    may span several WS frames (MQTT 5 §6.0 non-alignment)."""
    c = NativeWsClient(server.ws_port)
    c.handshake()
    c.mqtt_connect("nws-split")
    sub = serialize(P.Subscribe(packet_id=1,
                                topic_filters=[("s/+", {"qos": 0})]),
                    P.MQTT_V4)
    ping = serialize(P.PingReq(), P.MQTT_V4)
    # SUBSCRIBE + PINGREQ in ONE ws frame
    c.sock.sendall(encode_frame(OP_BINARY, sub + ping, mask=True))
    suback = c.recv_mqtt()
    assert isinstance(suback, P.SubAck)
    assert isinstance(c.recv_mqtt(), P.PingResp)
    # one PUBLISH split byte-by-byte across MANY ws frames
    pub = serialize(P.Publish(topic="s/x", payload=b"splitty", qos=0),
                    P.MQTT_V4)
    for b in pub:
        c.sock.sendall(encode_frame(OP_BINARY, bytes([b]), mask=True))
    got = c.recv_mqtt()
    assert isinstance(got, P.Publish) and got.payload == b"splitty"
    c.close()


def test_fragmented_data_message_reassembles(server):
    c = NativeWsClient(server.ws_port)
    c.handshake()
    c.mqtt_connect("nws-frag")
    c.send_mqtt(P.Subscribe(packet_id=1, topic_filters=[("f/+", {"qos": 0})]))
    c.recv_mqtt()
    pub = serialize(P.Publish(topic="f/a", payload=b"frag-payload", qos=0),
                    P.MQTT_V4)
    # binary FIN=0, continuation FIN=0, continuation FIN=1 — with a
    # PING interleaved between fragments (legal for control frames)
    a, b, d = pub[:3], pub[3:7], pub[7:]
    f1 = bytearray(encode_frame(OP_BINARY, a, mask=True))
    f1[0] &= 0x7F
    f2 = bytearray(encode_frame(0x0, b, mask=True))
    f2[0] &= 0x7F
    f3 = encode_frame(0x0, d, mask=True)
    c.sock.sendall(bytes(f1) + encode_frame(OP_PING, b"mid", mask=True)
                   + bytes(f2) + f3)
    got = c.recv_mqtt()
    assert isinstance(got, P.Publish) and got.payload == b"frag-payload"
    assert (OP_PONG, b"mid") in [c.control.pop()] or True
    c.close()


def test_malformed_mqtt_inside_ws_drops_conn(server):
    """An MQTT framing error arriving THROUGH the WS codec must tear
    the conn down (the drop is deferred until the decoder unwinds —
    round-7 review hardening: a Drop inside the decoder's own callback
    destroyed the decoder mid-Feed)."""
    c = NativeWsClient(server.ws_port)
    c.handshake()
    c.mqtt_connect("nws-badmqtt")
    # type nibble 0 is an invalid MQTT fixed header (Framer kBadType)
    c.send_frame(OP_BINARY, b"\x00\x00")
    c.sock.settimeout(10)
    # server closes; any close frame is acceptable, then EOF
    while True:
        data = c.sock.recv(4096)
        if not data:
            break
    c.close()
    # the host keeps serving other conns
    c2 = NativeWsClient(server.ws_port)
    c2.handshake()
    c2.mqtt_connect("nws-after-bad")
    c2.close()


def test_ping_pong_keepalive(server):
    c = NativeWsClient(server.ws_port)
    c.handshake()
    c.mqtt_connect("nws-ping")
    c.send_frame(OP_PING, b"hb-payload")
    op, payload = c.recv_control()
    assert (op, payload) == (OP_PONG, b"hb-payload")
    c.send_frame(OP_PING, b"")       # empty ping: empty pong
    op, payload = c.recv_control()
    assert (op, payload) == (OP_PONG, b"")
    assert server.fast_stats()["ws_pings"] >= 2
    c.close()


def test_close_code_echo(server):
    c = NativeWsClient(server.ws_port)
    c.handshake()
    c.mqtt_connect("nws-close")
    c.send_frame(OP_CLOSE, struct.pack(">H", 1000))
    op, payload = c.recv_control()
    assert op == OP_CLOSE
    assert struct.unpack(">H", payload[:2])[0] == 1000
    assert server.fast_stats()["ws_closes"] >= 1
    c.close()


# -- MQTT semantics over the native WS plane -----------------------------------

def test_qos1_pubsub_over_native_ws_fast_path(server):
    """QoS1 end-to-end over WS with the fast path engaged: the second
    publish onto a warmed topic must be served natively (fast_in moves)
    and the delivery pid must come from the NATIVE pid space."""
    sub = NativeWsClient(server.ws_port)
    sub.handshake()
    sub.mqtt_connect("nws-q1-sub")
    sub.send_mqtt(P.Subscribe(packet_id=1,
                              topic_filters=[("q1/t", {"qos": 1})]))
    assert sub.recv_mqtt().reason_codes == [1]

    pub = NativeWsClient(server.ws_port)
    pub.handshake()
    pub.mqtt_connect("nws-q1-pub")
    base_fast = server.fast_stats()["fast_in"]
    native_pid_seen = False
    for i in range(40):
        pub.send_mqtt(P.Publish(topic="q1/t", payload=b"m%d" % i, qos=1,
                                packet_id=i + 1))
        assert pub.recv_mqtt().packet_id == i + 1        # PUBACK
        got = sub.recv_mqtt()
        assert isinstance(got, P.Publish) and got.payload == b"m%d" % i
        assert got.qos == 1
        sub.send_mqtt(P.PubAck(packet_id=got.packet_id))  # free the slot
        if got.packet_id >= 32768:
            native_pid_seen = True
        time.sleep(0.005)     # let the permit grant land mid-run
    st = server.fast_stats()
    assert st["fast_in"] > base_fast, "fast path never engaged over WS"
    assert native_pid_seen, "no delivery used the native pid space"
    assert st["native_acks"] > 0, st
    sub.close()
    pub.close()


def test_ws_and_tcp_interop_same_host(server):
    """A TCP publisher reaches a WS subscriber through the same C++
    host — the two listeners share one conn table and fan-out plane."""
    from emqx_tpu.mqtt.frame import Parser as MqttParser

    sub = NativeWsClient(server.ws_port)
    sub.handshake()
    sub.mqtt_connect("nws-x-sub")
    sub.send_mqtt(P.Subscribe(packet_id=1,
                              topic_filters=[("x/#", {"qos": 0})]))
    sub.recv_mqtt()

    t = socket.create_connection(("127.0.0.1", server.port))
    t.settimeout(10)
    parser = MqttParser()
    t.sendall(serialize(P.Connect(clientid="tcp-x-pub"), P.MQTT_V4))
    pkts: list = []
    while not pkts:
        pkts.extend(parser.feed(t.recv(4096)))
    assert pkts.pop(0).reason_code == 0
    for i in range(3):
        t.sendall(serialize(P.Publish(topic="x/y", payload=b"c%d" % i,
                                      qos=0), P.MQTT_V4))
        got = sub.recv_mqtt()
        assert got.payload == b"c%d" % i
    t.close()
    sub.close()


def test_rejected_upgrade_falls_back_to_asyncio_plane(server):
    """The deployment story: the native listener serves ONLY /mqtt; an
    endpoint it rejects is served by the asyncio WS listener on the
    same app (broker/ws.py, the slow-plane oracle)."""
    import asyncio

    from emqx_tpu.broker.ws import WsBrokerServer

    # native listener: 400 for the alternate path
    s = socket.create_connection(("127.0.0.1", server.ws_port))
    s.settimeout(10)
    s.sendall(b"GET /mqtt-v2 HTTP/1.1\r\nHost: x\r\n"
              b"Upgrade: websocket\r\nConnection: Upgrade\r\n"
              b"Sec-WebSocket-Key: AAAAAAAAAAAAAAAAAAAAAA==\r\n\r\n")
    assert b"400" in s.recv(4096)
    s.close()

    async def main():
        ws = WsBrokerServer(port=0, app=server.app, path="/mqtt-v2")
        await ws.start()
        try:
            r, w = await asyncio.open_connection("127.0.0.1", ws.port)
            key = base64.b64encode(os.urandom(16)).decode()
            w.write((f"GET /mqtt-v2 HTTP/1.1\r\nHost: x\r\n"
                     "Upgrade: websocket\r\nConnection: Upgrade\r\n"
                     f"Sec-WebSocket-Key: {key}\r\n\r\n").encode())
            resp = await asyncio.wait_for(r.readuntil(b"\r\n\r\n"), 10)
            assert b"101" in resp.split(b"\r\n")[0]
            w.close()
        finally:
            await ws.stop()

    asyncio.run(main())


def test_oversized_handshake_dropped(server):
    s = socket.create_connection(("127.0.0.1", server.ws_port))
    s.settimeout(10)
    try:
        s.sendall(b"GET /mqtt HTTP/1.1\r\n" + b"X-Pad: " + b"a" * 20000)
        # server must drop rather than buffer forever
        assert s.recv(4096) == b""
    except (ConnectionResetError, BrokenPipeError):
        pass
    finally:
        s.close()
