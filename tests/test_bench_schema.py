"""Bench-artifact schema lint (ISSUE 17 satellite): every committed
BENCH_r*.json must carry the fields the bench exists to capture, so a
future run can't silently drop them the way r05 dropped
``kernel_platform`` (renamed to ``platform`` by _compose and discarded).

The artifact wrapper is driver-written: ``{"n", "cmd", "rc", "tail",
"parsed"}`` with the bench's own cumulative JSON line under ``parsed``.

Grandfathering is explicit and frozen: rounds that PREDATE a field are
exempt from it (r01–r04 predate the probe capture, r05 predates
kernel_platform retention and the tenm/sharded arms); everything from
r06 on must carry the full set.
"""

import json
import os
import re

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# fields → first round REQUIRED to carry them
PROBE_KEYS_SINCE = 5          # probe_ok / probe_log landed in r05
PLATFORM_KEY_SINCE = 6        # kernel_platform retention (this issue)
TENM_KEYS_SINCE = 6           # the standing 10M capture + sharded arm
KERNEL_TELEMETRY_KEYS_SINCE = 7   # ISSUE 19: stage percentiles + the
#                                   counters-overhead interleaved pair

TENM_KEYS = (
    "tenm_platform",
    "tenm_build_s",
    "tenm_device_gib",
    "tenm_topics_per_sec",
    "tenm_sync_p99_ms",
)
SHARDED_ARM_KEYS = (
    "tenm_sharded_shards",
    "tenm_sharded_mesh",
    "tenm_sharded_topics_per_sec",
    "tenm_sharded_sync_p99_ms",
)
KERNEL_TELEMETRY_KEYS = (
    "kernel_submit_p50_us",
    "kernel_submit_p99_us",
    "kernel_step_p50_us",
    "kernel_step_p99_us",
    "kernel_decode_p50_us",
    "kernel_decode_p99_us",
    "kernel_counters_on_topics_per_sec",
    "kernel_counters_off_topics_per_sec",
    "kernel_counters_overhead_frac",
    "kernel_counters_within_2pct_budget",
)


def _artifacts():
    out = []
    for name in sorted(os.listdir(REPO)):
        m = re.fullmatch(r"BENCH_r(\d+)\.json", name)
        if m:
            out.append((int(m.group(1)), os.path.join(REPO, name)))
    return out


ARTIFACTS = _artifacts()


def test_artifacts_exist():
    assert ARTIFACTS, "no BENCH_r*.json artifacts committed"


@pytest.mark.parametrize(
    "rnd,path", ARTIFACTS, ids=[f"r{r:02d}" for r, _ in ARTIFACTS])
def test_bench_artifact_schema(rnd, path):
    with open(path) as f:
        wrapper = json.load(f)
    for key in ("n", "cmd", "rc", "tail", "parsed"):
        assert key in wrapper, f"r{rnd:02d}: wrapper missing {key!r}"
    assert wrapper["n"] == rnd, (
        f"r{rnd:02d}: wrapper n={wrapper['n']} != filename round")
    parsed = wrapper["parsed"] or {}

    if rnd >= PROBE_KEYS_SINCE:
        assert "probe_ok" in parsed, f"r{rnd:02d}: missing probe_ok"
        assert "probe_log" in parsed, f"r{rnd:02d}: missing probe_log"

    if rnd >= PLATFORM_KEY_SINCE:
        assert "kernel_platform" in parsed, (
            f"r{rnd:02d}: missing kernel_platform — _compose must keep "
            f"the raw capture key alongside the 'platform' label")
        # probe resolution: ok, or a bounded-degradation reason — a
        # hang (probe_ok=false with no recorded reason) is the r05
        # failure mode this issue retired
        if not parsed.get("probe_ok"):
            assert parsed.get("probe_degraded_reason"), (
                f"r{rnd:02d}: probe_ok is false without a "
                f"probe_degraded_reason")

    if rnd >= TENM_KEYS_SINCE:
        for key in TENM_KEYS + SHARDED_ARM_KEYS:
            assert key in parsed, f"r{rnd:02d}: missing {key}"

    if rnd >= KERNEL_TELEMETRY_KEYS_SINCE:
        for key in KERNEL_TELEMETRY_KEYS:
            assert key in parsed, f"r{rnd:02d}: missing {key}"
