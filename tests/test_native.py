"""Native (C++) layer tests: framer parity with the Python parser, and
end-to-end MQTT over the epoll connection host."""

import asyncio
import random

import pytest

from emqx_tpu import native
from emqx_tpu.mqtt import packet as P
from emqx_tpu.mqtt.frame import Parser, parse_one, serialize

pytestmark = pytest.mark.skipif(
    not native.available(),
    reason=f"native lib unavailable: {native.build_error()}")


def _sample_packets():
    return [
        P.Connect(clientid="c1", keepalive=30),
        P.Subscribe(packet_id=1, topic_filters=[("a/+/c", {"qos": 1})]),
        P.Publish(topic="a/b/c", payload=b"x" * 300, qos=1, packet_id=2),
        P.PingReq(),
        P.Publish(topic="t", payload=b"", qos=0),
        P.Unsubscribe(packet_id=3, topic_filters=["a/+/c"]),
        P.Disconnect(),
    ]


def test_framer_matches_python_parser_random_chunking():
    wire = b"".join(serialize(p) for p in _sample_packets()) * 5
    rng = random.Random(42)
    for _ in range(20):
        nf = native.NativeFramer()
        frames = []
        pos = 0
        while pos < len(wire):
            n = rng.randint(1, 37)
            frames.extend(nf.feed(wire[pos:pos + n]))
            pos += n
        nf.close()
        # reassembled frames must concatenate back to the exact wire bytes
        assert b"".join(frames) == wire
        # each frame parses as exactly one packet, same as Python's parser
        py = Parser()
        expected = py.feed(wire)
        got = [parse_one(f) for f in frames]
        assert [type(p) for p in got] == [type(p) for p in expected]
        for a, b in zip(got, expected):
            if isinstance(a, P.Publish):
                assert (a.topic, a.payload, a.qos) == (b.topic, b.payload, b.qos)


def test_framer_rejects_oversize():
    nf = native.NativeFramer(max_size=64)
    big = serialize(P.Publish(topic="t", payload=b"y" * 1000, qos=0))
    with pytest.raises(ValueError):
        nf.feed(big)
    nf.close()


def test_framer_zero_length_body():
    nf = native.NativeFramer()
    frames = nf.feed(serialize(P.PingReq()) * 3)
    assert frames == [b"\xc0\x00"] * 3
    nf.close()


def test_native_host_end_to_end_pubsub():
    from emqx_tpu.broker.native_server import NativeBrokerServer
    from emqx_tpu.mqtt.client import MqttClient

    server = NativeBrokerServer(port=0)
    server.start()
    try:
        async def scenario():
            sub = MqttClient(port=server.port, clientid="nsub")
            pub = MqttClient(port=server.port, clientid="npub")
            assert (await sub.connect()).reason_code == 0
            await pub.connect()
            suback = await sub.subscribe("room/+/temp", qos=1)
            assert suback.reason_codes == [1]
            await pub.publish("room/7/temp", b"19.5", qos=1)
            got = await sub.recv()
            assert got.topic == "room/7/temp" and got.payload == b"19.5"
            await pub.publish("room/7/temp", b"20.0", qos=2)
            got = await sub.recv()
            assert got.payload == b"20.0"
            await sub.disconnect()
            await pub.disconnect()
        asyncio.run(scenario())
    finally:
        server.stop()


def test_native_host_many_clients_fanout():
    from emqx_tpu.broker.native_server import NativeBrokerServer
    from emqx_tpu.mqtt.client import MqttClient

    server = NativeBrokerServer(port=0)
    server.start()
    try:
        async def scenario():
            subs = [MqttClient(port=server.port, clientid=f"s{i}")
                    for i in range(8)]
            for s in subs:
                await s.connect()
                await s.subscribe("fan/#", qos=0)
            pub = MqttClient(port=server.port, clientid="fp")
            await pub.connect()
            await pub.publish("fan/out", b"hello")
            for s in subs:
                got = await s.recv()
                assert got.payload == b"hello"
            for s in subs:
                await s.disconnect()
            await pub.disconnect()
        asyncio.run(scenario())
    finally:
        server.stop()
