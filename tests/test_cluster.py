"""Cluster plane tests — the multi-node scenarios the reference covers
with peer BEAM nodes (emqx_shared_sub_SUITE cross-node dispatch,
emqx_router_helper_SUITE purge-on-nodedown, takeover suites), run here
on in-process nodes with the real replication/RPC/codec stack."""

import pytest

from emqx_tpu.broker.channel import Channel
from emqx_tpu.cluster import bpapi, codec
from emqx_tpu.cluster.harness import make_cluster, stop, sync
from emqx_tpu.cluster.transport import LocalBus, TransportError
from emqx_tpu.core.message import Message, SubOpts
from emqx_tpu.mqtt import packet as P


def connect(node, clientid, clean_start=True, proto=P.MQTT_V5, props=None):
    ch = Channel(node.app.broker, node.app.cm)
    out = ch.handle_in(P.Connect(proto_ver=proto, clientid=clientid,
                                 clean_start=clean_start,
                                 properties=props or {}))
    assert out[0].reason_code == P.RC_SUCCESS, out[0]
    return ch


def publishes(ch):
    return [p for p in ch.outbox if isinstance(p, P.Publish)]


# -- codec -----------------------------------------------------------------

def test_codec_roundtrip_bytes_tuples():
    obj = {"dest": ("g", "node2"), "payload": b"\x00\xffbin",
           "n": 3, "arr": [("a", 1), b"x"], "s": "txt"}
    assert codec.decode(codec.encode(obj)) == obj


def test_codec_message_roundtrip():
    m = Message(topic="t/1", payload=b"\x01\x02", qos=2, from_="c9",
                flags={"retain": True}, headers={"username": "u"})
    m2 = codec.msg_from_dict(codec.decode(codec.encode(
        codec.msg_to_dict(m))))
    assert (m2.topic, m2.payload, m2.qos, m2.from_) == \
        ("t/1", b"\x01\x02", 2, "c9")
    assert m2.retain and m2.headers["username"] == "u"


# -- bpapi -----------------------------------------------------------------

def test_bpapi_snapshot_frozen():
    """The BPAPI compatibility snapshot (emqx_bpapi_static_checks
    analogue): changing any released proto signature fails this test —
    add a new version instead."""
    assert bpapi.snapshot() == {
        "broker_v1": {"dispatch": ["filter", "msg"]},
        "cm_v1": {"kick": ["clientid"], "lookup": ["clientid"],
                  "takeover": ["clientid"]},
        "excl_v1": {"release": ["from_node", "topic", "sid"],
                    "sync": ["from_node", "holders"],
                    "try": ["from_node", "topic", "sid"]},
        "node_v1": {"bye": ["node"], "hello": ["node", "versions"],
                    "ping": ["node"]},
        "rlog_v1": {"apply_deltas": ["from_node", "deltas"],
                    "bootstrap": ["from_node"],
                    "registry_delta": ["from_node", "op", "clientid"],
                    "shared_delta": ["from_node", "op", "group", "topic",
                                     "sid"]},
        "shared_sub_v1": {"deliver": ["sid", "sub_topic", "msg"]},
    }


def test_bpapi_negotiate():
    assert bpapi.negotiate({"rlog": [1, 2]}, "rlog") == 1
    with pytest.raises(ValueError):
        bpapi.negotiate({"rlog": [9]}, "rlog")


# -- routing across nodes --------------------------------------------------

def test_cross_node_publish():
    nodes = make_cluster(2)
    n1, n2 = nodes
    sub = connect(n2, "sub1")
    sub.handle_in(P.Subscribe(packet_id=1,
                              topic_filters=[("t/+", {"qos": 0})]))
    sync(nodes)
    assert n1.app.broker.router.has_route("t/+", "node2")
    pub = connect(n1, "pub1")
    pub.handle_in(P.Publish(topic="t/x", qos=0, payload=b"hello"))
    got = publishes(sub)
    assert len(got) == 1 and got[0].payload == b"hello"
    assert n1.app.metrics.val("messages.forward") == 1
    stop(nodes)


def test_route_delete_replicates():
    nodes = make_cluster(3)
    n1, n2, n3 = nodes
    sub = connect(n3, "s3")
    sub.handle_in(P.Subscribe(packet_id=1,
                              topic_filters=[("a/#", {"qos": 0})]))
    sync(nodes)
    assert n1.app.broker.router.has_route("a/#", "node3")
    sub.handle_in(P.Unsubscribe(packet_id=2, topic_filters=["a/#"]))
    sync(nodes)
    assert not n1.app.broker.router.has_route("a/#", "node3")
    assert not n2.app.broker.router.has_route("a/#", "node3")
    stop(nodes)


def test_late_joiner_bootstraps_existing_routes():
    nodes = make_cluster(2)
    n1, n2 = nodes
    sub = connect(n1, "s1")
    sub.handle_in(P.Subscribe(packet_id=1,
                              topic_filters=[("x/#", {"qos": 0})]))
    sync(nodes)
    # third node joins later and must learn x/# → node1 via bootstrap
    from emqx_tpu.cluster.node import ClusterNode
    n3 = ClusterNode("node3", LocalBus("node3", n1.transport.fabric))
    n3.join(["node1"])
    assert n3.app.broker.router.has_route("x/#", "node1")
    pub = connect(n3, "p3")
    pub.handle_in(P.Publish(topic="x/1", qos=0, payload=b"late"))
    assert publishes(sub)[0].payload == b"late"
    nodes.append(n3)
    stop(nodes)


# -- shared subscriptions across nodes ------------------------------------

def test_shared_group_single_delivery_across_nodes():
    nodes = make_cluster(2, shared_strategy="round_robin")
    n1, n2 = nodes
    a = connect(n1, "a")
    a.handle_in(P.Subscribe(packet_id=1,
                            topic_filters=[("$share/g/t", {"qos": 0})]))
    b = connect(n2, "b")
    b.handle_in(P.Subscribe(packet_id=1,
                            topic_filters=[("$share/g/t", {"qos": 0})]))
    sync(nodes)
    pub = connect(n1, "p")
    for i in range(6):
        pub.handle_in(P.Publish(topic="t", qos=0,
                                payload=b"m%d" % i))
    # exactly one delivery per message, balanced across nodes
    na, nb = len(publishes(a)), len(publishes(b))
    assert na + nb == 6
    assert na == 3 and nb == 3            # round_robin balance
    stop(nodes)


def test_shared_member_down_redispatches_to_other_node():
    nodes = make_cluster(2, shared_strategy="round_robin")
    n1, n2 = nodes
    a = connect(n1, "a")
    a.handle_in(P.Subscribe(packet_id=1,
                            topic_filters=[("$share/g/t", {"qos": 1})]))
    b = connect(n2, "b")
    b.handle_in(P.Subscribe(packet_id=1,
                            topic_filters=[("$share/g/t", {"qos": 1})]))
    sync(nodes)
    # kill b: its node announces session gone
    b.handle_in(P.Disconnect())
    pub = connect(n1, "p")
    for i in range(4):
        pub.handle_in(P.Publish(topic="t", qos=1, packet_id=10 + i,
                                payload=b"x"))
    assert len(publishes(a)) == 4          # all land on the live member
    stop(nodes)


# -- takeover across nodes -------------------------------------------------

def test_cross_node_session_takeover():
    nodes = make_cluster(2)
    n1, n2 = nodes
    props = {"Session-Expiry-Interval": 3600}
    c1 = connect(n1, "dev", clean_start=False, props=props)
    c1.handle_in(P.Subscribe(packet_id=1,
                             topic_filters=[("d/#", {"qos": 1})]))
    sync(nodes)
    # client roams to node2, resumes
    ch2 = Channel(n2.app.broker, n2.app.cm)
    out = ch2.handle_in(P.Connect(proto_ver=P.MQTT_V5, clientid="dev",
                                  clean_start=False, properties=props))
    assert out[0].session_present is True
    assert "d/#" in ch2.session.subscriptions
    sync(nodes)
    # old node no longer owns it; routes moved
    assert n1.app.cm.lookup_channel("dev") is None
    assert n2.app.broker.router.has_route("d/#", "node2")
    assert not n1.app.broker.router.has_route("d/#", "node1")
    # publishes from node1 now reach the channel on node2
    pub = connect(n1, "p")
    pub.handle_in(P.Publish(topic="d/1", qos=1, packet_id=5,
                            payload=b"roam"))
    assert publishes(ch2)[0].payload == b"roam"
    stop(nodes)


def test_cross_node_clean_start_kicks_remote():
    nodes = make_cluster(2)
    n1, n2 = nodes
    c1 = connect(n1, "dup")
    ch2 = connect(n2, "dup", clean_start=True)
    sync(nodes)
    assert n1.app.cm.lookup_channel("dup") is None
    assert n2.app.cm.lookup_channel("dup") is ch2
    stop(nodes)


# -- failure handling ------------------------------------------------------

def test_nodedown_purges_routes_and_members():
    nodes = make_cluster(3)
    n1, n2, n3 = nodes
    s = connect(n3, "s3")
    s.handle_in(P.Subscribe(packet_id=1, topic_filters=[
        ("t/#", {"qos": 0}), ("$share/g/q", {"qos": 0})]))
    sync(nodes)
    assert n1.app.broker.router.has_route("t/#", "node3")
    # partition node3 away from both peers; their heartbeats fail
    fabric = n1.transport.fabric
    fabric.partition("node1", "node3")
    fabric.partition("node2", "node3")
    for _ in range(2):
        n1.tick()
        n2.tick()
    assert not n1.app.broker.router.has_route("t/#", "node3")
    assert not n2.app.broker.router.has_route("t/#", "node3")
    assert n1.app.shared.members() == {}
    # publish on n1 goes nowhere but doesn't error
    pub = connect(n1, "p")
    pub.handle_in(P.Publish(topic="t/1", qos=0, payload=b"x"))
    stop(nodes)


def test_partition_heal_resyncs():
    nodes = make_cluster(2)
    n1, n2 = nodes
    fabric = n1.transport.fabric
    fabric.partition("node1", "node2")
    for _ in range(2):
        n1.tick()
        n2.tick()
    assert "node2" not in n1.alive_peers()
    # while partitioned, node2 gains a subscriber
    s = connect(n2, "s2")
    s.handle_in(P.Subscribe(packet_id=1,
                            topic_filters=[("h/#", {"qos": 0})]))
    fabric.heal("node1", "node2")
    n1.tick()
    n2.tick()
    assert "node2" in n1.alive_peers()
    assert n1.app.broker.router.has_route("h/#", "node2")
    pub = connect(n1, "p")
    pub.handle_in(P.Publish(topic="h/i", qos=0, payload=b"healed"))
    assert publishes(s)[0].payload == b"healed"
    stop(nodes)


# -- TCP transport ---------------------------------------------------------

def test_tcp_transport_cluster():
    nodes = make_cluster(2, transport="tcp")
    n1, n2 = nodes
    try:
        sub = connect(n2, "tsub")
        sub.handle_in(P.Subscribe(packet_id=1,
                                  topic_filters=[("tt/#", {"qos": 0})]))
        sync(nodes)
        import time
        deadline = time.time() + 5
        while (not n1.app.broker.router.has_route("tt/#", "node2")
               and time.time() < deadline):
            time.sleep(0.01)
        assert n1.app.broker.router.has_route("tt/#", "node2")
        pub = connect(n1, "tpub")
        pub.handle_in(P.Publish(topic="tt/1", qos=0, payload=b"over-tcp"))
        deadline = time.time() + 5
        while not publishes(sub) and time.time() < deadline:
            time.sleep(0.01)
        assert publishes(sub)[0].payload == b"over-tcp"
    finally:
        stop(nodes)


def test_transport_error_on_unknown_node():
    fabric = LocalBus.Fabric()
    bus = LocalBus("n1", fabric)
    with pytest.raises(TransportError):
        bus.call("ghost", "node.ping", node="n1")


def test_tcp_handler_may_issue_blocking_calls():
    """Regression: RPC handlers run off the transport loop thread, so a
    handler that itself makes a blocking call back to the caller (the
    bootstrap-from-handler paths) must not deadlock the loop."""
    from emqx_tpu.cluster.transport import TcpTransport

    t1, t2 = TcpTransport("n1"), TcpTransport("n2")
    try:
        t1.add_peer("n2", t2.host, t2.port)
        t2.add_peer("n1", t1.host, t1.port)
        t1.register("echo", lambda x: x)
        t2.register("relay", lambda x: t2.call("n1", "echo", x=x) + 1)
        assert t1.call("n2", "relay", x=41, _timeout=5) == 42
    finally:
        t1.close()
        t2.close()


# -- $exclusive across nodes ------------------------------------------------

def test_exclusive_subscription_cluster_wide():
    """A client on node2 cannot take an $exclusive topic a node1 client
    holds (emqx_exclusive_subscription's cluster-wide transaction);
    unsubscribe releases it everywhere."""
    from emqx_tpu.broker.broker import ExclusiveLocked
    from emqx_tpu.core.message import SubOpts

    nodes = make_cluster(2)
    n1, n2 = nodes
    try:
        n1.app.broker.subscribe(
            "c1", "$exclusive/t/1", SubOpts(exclusive=True))
        sync(nodes)
        with pytest.raises(ExclusiveLocked):
            n2.app.broker.subscribe(
                "c2", "$exclusive/t/1", SubOpts(exclusive=True))
        # release on node1 → node2 can take it
        n1.app.broker.unsubscribe("c1", "$exclusive/t/1")
        sync(nodes)
        n2.app.broker.subscribe(
            "c2", "$exclusive/t/1", SubOpts(exclusive=True))
    finally:
        stop(nodes)


def test_exclusive_released_on_nodedown():
    nodes = make_cluster(2)
    n1, n2 = nodes
    try:
        n1.app.broker.subscribe(
            "c1", "$exclusive/t/2", SubOpts(exclusive=True))
        sync(nodes)
        assert n2.exclusive_remote["$exclusive/t/2"][0] == "c1"
        n2._nodedown("node1")
        assert "$exclusive/t/2" not in n2.exclusive_remote
        n2.app.broker.subscribe(
            "c2", "$exclusive/t/2", SubOpts(exclusive=True))
    finally:
        stop(nodes)


def test_exclusive_visible_to_late_joiner():
    """Bootstrap snapshot carries exclusive holders to a fresh node."""
    from emqx_tpu.broker.broker import ExclusiveLocked
    from emqx_tpu.cluster.node import ClusterNode

    nodes = make_cluster(2)
    try:
        nodes[0].app.broker.subscribe(
            "c1", "$exclusive/t/3", SubOpts(exclusive=True))
        sync(nodes)
        n3 = ClusterNode(
            "node3", LocalBus("node3", nodes[0].transport.fabric))
        n3.join(["node1"])
        nodes.append(n3)
        with pytest.raises(ExclusiveLocked):
            n3.app.broker.subscribe(
                "c9", "$exclusive/t/3", SubOpts(exclusive=True))
    finally:
        stop(nodes)


def test_tcp_transport_per_key_lanes_order_and_parallelism():
    """gen_rpc-analogue lanes: casts sharing a _key stay ordered on one
    connection; different keys ride parallel lanes (a slow key must not
    block another key's delivery)."""
    import threading
    import time as _t

    from emqx_tpu.cluster.transport import TcpTransport

    a = TcpTransport("la")
    b = TcpTransport("lb")
    a.add_peer("lb", b.host, b.port)
    got: list = []
    slow_started = threading.Event()
    fast_done = threading.Event()

    def handler(seq: int, key: str) -> None:
        if key == "slow" and seq == 0:
            slow_started.set()
            _t.sleep(1.0)
        got.append((key, seq))
        if key == "fast":
            fast_done.set()

    b.register("lane.probe", handler)
    try:
        # interleave: slow key first, then 50 ordered casts on key kA
        a.cast("lb", "lane.probe", _key="slow", seq=0, key="slow")
        assert slow_started.wait(5)
        for i in range(50):
            a.cast("lb", "lane.probe", _key="kA", seq=i, key="kA")
        a.cast("lb", "lane.probe", _key="fast", seq=0, key="fast")
        assert fast_done.wait(5), \
            "a slow lane blocked an unrelated key's lane"
        # deterministic settle (ISSUE 4 satellite): the explicit cast
        # barrier proves every frame is on the wire; the remaining wait
        # is only for the peer's sequential dispatch to drain them
        a.flush_casts(timeout=15)
        deadline = _t.time() + 10
        while _t.time() < deadline and \
                len([g for g in got if g[0] == "kA"]) < 50:
            _t.sleep(0.05)
        ka = [seq for key, seq in got if key == "kA"]
        assert ka == list(range(50)), "per-key order violated"
        # distinct lanes actually used (connection map keyed by lane)
        lanes = {lane for (_n, lane) in a._writers}
        assert len(lanes) >= 2
    finally:
        a.close()
        b.close()
