"""Exhook tests: provider load/dispatch, rewrite/drop on publish,
authenticate/authorize verdicts, failed_action semantics, batch RPC
(reference ground: emqx_exhook_SUITE + its demo gRPC server)."""

import pytest

from emqx_tpu.app import BrokerApp
from emqx_tpu.broker.channel import Channel
from emqx_tpu.core.message import Message
from emqx_tpu.exhook import proto
from emqx_tpu.exhook.provider import HookProvider, ProviderServer
from emqx_tpu.exhook.server import ExhookMgr, ExhookServer
from emqx_tpu.mqtt import packet as P


class RewritingProvider(HookProvider):
    """Rewrites topics under rw/, drops topics under blk/, denies
    user 'mallory', records lifecycle notifications."""

    def __init__(self) -> None:
        self.events: list[tuple] = []

    def on_client_authenticate(self, args):
        ci = args.get("clientinfo") or {}
        if ci.get("username") == "mallory":
            return {"type": proto.STOP_AND_RETURN,
                    "value": {"result": False}}
        return {"type": proto.CONTINUE}

    def on_client_authorize(self, args):
        if args.get("topic", "").startswith("forbidden/"):
            return {"type": proto.STOP_AND_RETURN,
                    "value": {"result": False}}
        return {"type": proto.CONTINUE}

    def on_message_publish(self, args):
        m = args["message"]
        if m["topic"].startswith("blk/"):
            return {"type": proto.STOP_AND_RETURN, "value": {"drop": True}}
        if m["topic"].startswith("rw/"):
            m = {**m, "topic": "rewritten/" + m["topic"][3:],
                 "payload": m["payload"] + b"!"}
            return {"type": proto.STOP_AND_RETURN,
                    "value": {"message": m}}
        return {"type": proto.CONTINUE}

    def on_client_connected(self, args):
        self.events.append(("connected", args))


@pytest.fixture()
def wired():
    prov = RewritingProvider()
    psrv = ProviderServer(prov)
    psrv.start()
    app = BrokerApp()
    mgr = ExhookMgr(metrics=app.metrics)
    mgr.attach(app.hooks)
    server = ExhookServer("default", psrv.host, psrv.port,
                          pool_size=2, timeout_s=2.0)
    wanted = mgr.enable(server)
    yield app, mgr, prov, psrv, wanted
    mgr.disable("default")
    psrv.stop()


def _connect(app, clientid="c1", username=None):
    ch = Channel(app.broker, app.cm)
    out = ch.handle_in(P.Connect(proto_ver=P.MQTT_V5, clientid=clientid,
                                 username=username))
    return ch, out


def test_provider_loaded_hooks(wired):
    _app, _mgr, _prov, _psrv, wanted = wired
    assert "message.publish" in wanted
    assert "client.authenticate" in wanted
    assert "client.authorize" in wanted
    assert "client.connected" in wanted
    assert "session.created" not in wanted        # not overridden


def test_exhook_authenticate_deny(wired):
    app, *_ = wired
    _ch, out = _connect(app, username="mallory")
    assert out[0].reason_code == P.RC_NOT_AUTHORIZED
    _ch, out = _connect(app, clientid="c2", username="alice")
    assert out[0].reason_code == P.RC_SUCCESS


def test_exhook_authorize_and_publish_rewrite(wired):
    app, *_ = wired
    watcher, _ = _connect(app, "w")
    watcher.handle_in(P.Subscribe(packet_id=1, topic_filters=[
        ("rewritten/#", {"qos": 0}), ("blk/#", {"qos": 0}),
        ("rw/#", {"qos": 0})]))
    dev, _ = _connect(app, "d")
    # rewrite: rw/x → rewritten/x with payload suffix
    dev.handle_in(P.Publish(topic="rw/x", qos=0, payload=b"data"))
    pubs = [p for p in watcher.outbox if isinstance(p, P.Publish)]
    assert len(pubs) == 1
    assert pubs[0].topic == "rewritten/x" and pubs[0].payload == b"data!"
    # drop: blk/* never delivered
    dev.handle_in(P.Publish(topic="blk/secret", qos=0, payload=b"x"))
    assert len([p for p in watcher.outbox
                if isinstance(p, P.Publish)]) == 1
    # authorize: forbidden/* → puback error
    acks = dev.handle_in(P.Publish(topic="forbidden/z", qos=1,
                                   packet_id=9, payload=b""))
    assert acks[0].reason_code == P.RC_NOT_AUTHORIZED


def test_exhook_notifications(wired):
    app, _mgr, prov, *_ = wired
    _connect(app, "notifyme")
    import time
    deadline = time.time() + 2
    while not prov.events and time.time() < deadline:
        time.sleep(0.01)
    assert prov.events and prov.events[0][0] == "connected"
    assert prov.events[0][1]["args"][0]["clientid"] == "notifyme"


def test_batch_publish_rpc(wired):
    _app, mgr, *_ = wired
    msgs = [Message(topic="rw/a", payload=b"1"),
            Message(topic="blk/b", payload=b"2"),
            Message(topic="ok/c", payload=b"3")]
    out = mgr.on_message_publish_batch(msgs)
    assert out[0].topic == "rewritten/a" and out[0].payload == b"1!"
    assert out[1] is None                          # dropped
    assert out[2].topic == "ok/c"                  # untouched


def test_failed_action_deny_vs_ignore():
    app = BrokerApp()
    mgr = ExhookMgr()
    mgr.attach(app.hooks)
    # no listener on this port → every call fails fast
    dead = ExhookServer("dead", "127.0.0.1", 9, pool_size=1,
                        timeout_s=0.2, failed_action="deny")
    dead.loaded = True
    dead.hooks_wanted = ["message.publish", "client.authenticate"]
    mgr.servers["dead"] = dead
    _ch, out = _connect(app, "x")
    assert out[0].reason_code == P.RC_NOT_AUTHORIZED   # deny on failure
    dead.failed_action = "ignore"
    _ch, out = _connect(app, "y")
    assert out[0].reason_code == P.RC_SUCCESS          # ignore on failure
    # publish with deny drops the message
    dead.failed_action = "deny"
    deliveries = app.broker.publish(Message(topic="t/1", payload=b"x"))
    assert deliveries == {}


def test_disable_removes_provider(wired):
    app, mgr, *_ = wired
    assert mgr.disable("default")
    _ch, out = _connect(app, "afterwards", username="mallory")
    assert out[0].reason_code == P.RC_SUCCESS      # no provider anymore
    assert not mgr.disable("default")
