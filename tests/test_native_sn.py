"""The native (C++) MQTT-SN gateway plane — sn.h/host.cc driven against
gateway/mqttsn.py as the protocol oracle: every test client speaks the
ORACLE's codec over real UDP sockets, so any disagreement between the
two MQTT-SN implementations fails here, and one shared vector set locks
the codecs together byte-for-byte.

Covers: the shared codec vectors (parse+serialize parity incl. the
malformed-length set), CONNECT/REGISTER/SUBSCRIBE/PUBLISH end-to-end on
the native plane, topic-id registry edges (idempotent REGISTER,
invalid-id PUBACK, wildcard tid 0), the QoS -1 publish-without-connect
lane, the fast-path permit ride, qos1 retransmit-on-timeout through the
ack plane's inflight bitmaps, qos2 over SN (PUBREC/PUBREL/PUBCOMP),
sleep-mode buffering until PINGREQ, retained-on-subscribe parity across
SN/TCP/WS against the Python retainer oracle, the props-fallback
degradation, and the asyncio-gateway deployment fallback."""

import socket
import struct
import threading
import time

import pytest

from emqx_tpu import native
from emqx_tpu.core.message import Message
from emqx_tpu.gateway import mqttsn as SN

pytestmark = pytest.mark.skipif(
    not native.available(), reason=f"native lib: {native.build_error()}")


@pytest.fixture()
def app():
    from emqx_tpu.app import BrokerApp

    return BrokerApp()


@pytest.fixture()
def server(app):
    from emqx_tpu.broker.native_server import NativeBrokerServer

    srv = NativeBrokerServer(
        port=0, app=app, sn_port=0, ws_port=0,
        sn_predefined={1: "pre/defined", 7: "pre/seven"},
        session_opts={"max_inflight": 32})
    srv.start()
    yield srv
    srv.stop()


class SnSock:
    """Blocking UDP client speaking the ORACLE's codec (SN.Frame)."""

    def __init__(self, port: int):
        self.f = SN.Frame()
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.sock.settimeout(5)
        self.sock.connect(("127.0.0.1", port))
        self.inbox: list = []

    def send(self, m: SN.SnMessage) -> None:
        self.sock.send(self.f.serialize(m))

    def recv(self, timeout: float = 5.0) -> SN.SnMessage:
        self.sock.settimeout(timeout)
        while not self.inbox:
            data = self.sock.recv(65536)
            self.inbox.extend(self.f.parse(data, None)[0])
        return self.inbox.pop(0)

    def recv_until(self, type_, timeout: float = 5.0) -> SN.SnMessage:
        deadline = time.time() + timeout
        while time.time() < deadline:
            m = self.recv(timeout=max(0.1, deadline - time.time()))
            if m.type == type_:
                return m
        raise AssertionError(f"no SN message of type {type_}")

    def connect(self, cid: str, duration: int = 60,
                clean: bool = True) -> SN.SnMessage:
        self.send(SN.SnMessage(SN.CONNECT,
                               flags=SN.F_CLEAN if clean else 0,
                               duration=duration, clientid=cid))
        ack = self.recv()
        assert ack.type == SN.CONNACK and ack.rc == SN.RC_ACCEPTED, (
            ack.type, ack.rc)
        return ack

    def close(self):
        self.sock.close()


# ---------------------------------------------------------------------------
# shared codec vectors: the oracle codec and sn.h must agree byte-level
# ---------------------------------------------------------------------------

def _vectors() -> list:
    qf = SN.qos_flags
    big = b"y" * 400                    # forces the 3-byte length form
    return [
        SN.SnMessage(SN.CONNECT, flags=SN.F_CLEAN, duration=30,
                     clientid="dev-1"),
        SN.SnMessage(SN.CONNECT, flags=SN.F_CLEAN | SN.F_WILL,
                     duration=0, clientid=""),
        SN.SnMessage(SN.CONNACK, rc=SN.RC_NOT_SUPPORTED),
        SN.SnMessage(SN.REGISTER, topic_id=0, msg_id=9,
                     topic_name="sensors/t1"),
        SN.SnMessage(SN.REGACK, topic_id=3, msg_id=9, rc=0),
        SN.SnMessage(SN.PUBLISH, flags=qf(0), topic_id=3, msg_id=0,
                     data=b"hello"),
        SN.SnMessage(SN.PUBLISH, flags=qf(1) | SN.F_RETAIN, topic_id=3,
                     msg_id=11, data=b"r"),
        SN.SnMessage(SN.PUBLISH, flags=qf(2) | SN.F_DUP, topic_id=3,
                     msg_id=12, data=b""),
        SN.SnMessage(SN.PUBLISH, flags=qf(-1) | SN.TID_PREDEF,
                     topic_id=1, data=b"fire"),
        SN.SnMessage(SN.PUBLISH, flags=qf(0), topic_id=3, msg_id=0,
                     data=big),
        SN.SnMessage(SN.PUBACK, topic_id=3, msg_id=11,
                     rc=SN.RC_INVALID_TOPIC_ID),
        SN.SnMessage(SN.PUBREC, msg_id=12),
        SN.SnMessage(SN.PUBREL, msg_id=12),
        SN.SnMessage(SN.PUBCOMP, msg_id=12),
        SN.SnMessage(SN.SUBSCRIBE, flags=qf(1), msg_id=2,
                     topic_name="sensors/#"),
        SN.SnMessage(SN.SUBSCRIBE, flags=qf(0) | SN.TID_PREDEF,
                     msg_id=3, topic_id=7),
        SN.SnMessage(SN.SUBSCRIBE, flags=qf(0) | SN.TID_SHORT,
                     msg_id=4, topic_name="ab"),
        SN.SnMessage(SN.SUBACK, flags=qf(1), topic_id=5, msg_id=2,
                     rc=0),
        SN.SnMessage(SN.UNSUBSCRIBE, flags=qf(0), msg_id=5,
                     topic_name="sensors/#"),
        SN.SnMessage(SN.UNSUBACK, msg_id=5),
        SN.SnMessage(SN.PINGREQ),
        SN.SnMessage(SN.PINGREQ, clientid="sleeper-1"),
        SN.SnMessage(SN.PINGRESP),
        SN.SnMessage(SN.DISCONNECT),
        SN.SnMessage(SN.DISCONNECT, duration=120),
        SN.SnMessage(SN.SEARCHGW, rc=2),
        SN.SnMessage(SN.GWINFO, rc=1),
        SN.SnMessage(SN.ADVERTISE, rc=1, duration=900),
    ]


def test_codec_vectors_shared():
    """Every vector's oracle parse→reserialize must equal the native
    codec's parse→reserialize of the SAME datagram — the lock that
    keeps the two MQTT-SN implementations from drifting apart."""
    f = SN.Frame()
    for m in _vectors():
        wire = f.serialize(m)
        # oracle roundtrip
        parsed, _ = f.parse(wire, None)
        assert len(parsed) == 1, m
        oracle_bytes = f.serialize(parsed[0])
        # native roundtrip of the same wire bytes
        n, native_bytes = native.sn_roundtrip(wire)
        assert n == 1, m
        assert native_bytes == oracle_bytes, (
            f"codec drift on type {m.type}: "
            f"native={native_bytes!r} oracle={oracle_bytes!r}")
    # several messages in one datagram parse identically too
    blob = b"".join(f.serialize(m) for m in _vectors()[:6])
    n, native_bytes = native.sn_roundtrip(blob)
    parsed, _ = f.parse(blob, None)
    assert n == len(parsed) == 6
    assert native_bytes == b"".join(f.serialize(p) for p in parsed)


def test_codec_malformed_lengths_terminate():
    """The malformed-length set must yield ZERO messages on both
    planes instead of spinning or over-reading."""
    f = SN.Frame()
    for bad in (b"\x00", b"\x01", b"\x01\x00", b"\x01\x00\x00",
                b"\x01\x00\x02\x00", b"\x05\x0c\x00", b"\x02"):
        pkts, _ = f.parse(bad, None)
        n, out = native.sn_roundtrip(bad)
        assert pkts == [] and n == 0 and out == b"", bad


# ---------------------------------------------------------------------------
# native gateway end-to-end
# ---------------------------------------------------------------------------

def test_register_publish_subscribe_e2e(server):
    pub = SnSock(server.sn_port)
    sub = SnSock(server.sn_port)
    pub.connect("sn-pub")
    sub.connect("sn-sub")
    pub.send(SN.SnMessage(SN.REGISTER, msg_id=1,
                          topic_name="sensors/t1"))
    ra = pub.recv()
    assert ra.type == SN.REGACK and ra.rc == SN.RC_ACCEPTED and \
        ra.topic_id > 0
    sub.send(SN.SnMessage(SN.SUBSCRIBE, flags=SN.qos_flags(1), msg_id=2,
                          topic_name="sensors/#"))
    sa = sub.recv()
    assert sa.type == SN.SUBACK and sa.rc == SN.RC_ACCEPTED
    assert SN.qos_of(sa.flags) == 1          # granted (capped) qos
    assert sa.topic_id == 0                  # wildcard: no id
    pub.send(SN.SnMessage(SN.PUBLISH, flags=SN.qos_flags(1),
                          topic_id=ra.topic_id, msg_id=3, data=b"21.5"))
    pa = pub.recv()
    assert pa.type == SN.PUBACK and pa.rc == SN.RC_ACCEPTED
    assert (pa.topic_id, pa.msg_id) == (ra.topic_id, 3)
    reg = sub.recv_until(SN.REGISTER)        # auto-REGISTER on deliver
    assert reg.topic_name == "sensors/t1" and reg.topic_id > 0
    dlv = sub.recv_until(SN.PUBLISH)
    assert dlv.data == b"21.5" and dlv.topic_id == reg.topic_id
    assert SN.qos_of(dlv.flags) == 1
    sub.send(SN.SnMessage(SN.PUBACK, topic_id=dlv.topic_id,
                          msg_id=dlv.msg_id))
    pub.close()
    sub.close()


def test_topic_id_registry_edges(server):
    c = SnSock(server.sn_port)
    c.connect("sn-reg")
    # idempotent REGISTER: same topic, same id
    c.send(SN.SnMessage(SN.REGISTER, msg_id=1, topic_name="a/b"))
    t1 = c.recv().topic_id
    c.send(SN.SnMessage(SN.REGISTER, msg_id=2, topic_name="a/b"))
    assert c.recv().topic_id == t1
    c.send(SN.SnMessage(SN.REGISTER, msg_id=3, topic_name="a/c"))
    assert c.recv().topic_id != t1
    # unregistered id: qos1 publish answers INVALID_TOPIC_ID
    c.send(SN.SnMessage(SN.PUBLISH, flags=SN.qos_flags(1),
                        topic_id=0x4242, msg_id=4, data=b"x"))
    pa = c.recv()
    assert pa.type == SN.PUBACK and pa.rc == SN.RC_INVALID_TOPIC_ID
    # predefined subscribe echoes the predefined id in the SUBACK
    c.send(SN.SnMessage(SN.SUBSCRIBE,
                        flags=SN.qos_flags(0) | SN.TID_PREDEF,
                        msg_id=5, topic_id=7))
    sa = c.recv()
    assert sa.type == SN.SUBACK and sa.rc == SN.RC_ACCEPTED
    assert sa.topic_id == 7
    # unknown predefined subscribe: INVALID_TOPIC_ID
    c.send(SN.SnMessage(SN.SUBSCRIBE,
                        flags=SN.qos_flags(0) | SN.TID_PREDEF,
                        msg_id=6, topic_id=99))
    sa = c.recv()
    assert sa.type == SN.SUBACK and sa.rc == SN.RC_INVALID_TOPIC_ID
    c.close()


def test_qos_minus_one_predefined(server, app):
    seen = []
    app.hooks.add("message.publish",
                  lambda m: seen.append((m.topic, m.payload)) or None,
                  priority=-500)
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    s.connect(("127.0.0.1", server.sn_port))
    f = SN.Frame()
    # no CONNECT at all: the spec's QoS -1 fire-and-forget
    s.send(f.serialize(SN.SnMessage(
        SN.PUBLISH, flags=SN.qos_flags(-1) | SN.TID_PREDEF,
        topic_id=1, data=b"fire")))
    deadline = time.time() + 5
    while time.time() < deadline and ("pre/defined", b"fire") not in seen:
        time.sleep(0.02)
    assert ("pre/defined", b"fire") in seen
    # unknown predefined id: silently dropped, nothing published
    n0 = len(seen)
    s.send(f.serialize(SN.SnMessage(
        SN.PUBLISH, flags=SN.qos_flags(-1) | SN.TID_PREDEF,
        topic_id=55, data=b"ghost")))
    time.sleep(0.3)
    assert len(seen) == n0
    assert server.fast_stats()["sn_qos_m1"] >= 2
    s.close()


def test_sn_rides_the_fast_path(server):
    """After the permit warms, SN publishes are consumed natively
    (fast_in grows, punts stop) — the identical machinery TCP rides."""
    pub = SnSock(server.sn_port)
    sub = SnSock(server.sn_port)
    pub.connect("sn-fast-p")
    sub.connect("sn-fast-s")
    pub.send(SN.SnMessage(SN.REGISTER, msg_id=1, topic_name="fast/t"))
    tid = pub.recv().topic_id
    sub.send(SN.SnMessage(SN.SUBSCRIBE, flags=SN.qos_flags(0), msg_id=2,
                          topic_name="fast/t"))
    sub.recv_until(SN.SUBACK)
    # first publish earns the permit on the full Python path
    pub.send(SN.SnMessage(SN.PUBLISH, flags=SN.qos_flags(0),
                          topic_id=tid, data=b"warm"))
    sub.recv_until(SN.PUBLISH)
    time.sleep(0.4)          # the grant runs on an idle poll step
    base = server.fast_stats()
    n = 50
    for i in range(n):
        pub.send(SN.SnMessage(SN.PUBLISH, flags=SN.qos_flags(0),
                              topic_id=tid, data=b"m%d" % i))
    got = 0
    deadline = time.time() + 10
    while got < n and time.time() < deadline:
        m = sub.recv_until(SN.PUBLISH, timeout=deadline - time.time())
        got += 1
    assert got == n
    stats = server.fast_stats()
    assert stats["fast_in"] - base["fast_in"] >= n, (base, stats)
    assert stats["sn_in"] - base["sn_in"] >= n
    assert stats["sn_out"] - base["sn_out"] >= n
    pub.close()
    sub.close()


def test_qos1_retransmit_via_ack_plane(server):
    """An unacked native qos1 delivery over UDP retransmits with DUP
    (the ack plane's inflight bitmap is the authority); the PUBACK
    stops the retransmits and frees the slot."""
    pub = SnSock(server.sn_port)
    sub = SnSock(server.sn_port)
    pub.connect("sn-rx-p")
    sub.connect("sn-rx-s")
    pub.send(SN.SnMessage(SN.REGISTER, msg_id=1, topic_name="rx/t"))
    tid = pub.recv().topic_id
    sub.send(SN.SnMessage(SN.SUBSCRIBE, flags=SN.qos_flags(1), msg_id=2,
                          topic_name="rx/t"))
    sub.recv_until(SN.SUBACK)
    # warm the permit so the delivery rides the native ack plane
    pub.send(SN.SnMessage(SN.PUBLISH, flags=SN.qos_flags(1),
                          topic_id=tid, msg_id=3, data=b"w"))
    pub.recv_until(SN.PUBACK)
    first = sub.recv_until(SN.PUBLISH)
    sub.send(SN.SnMessage(SN.PUBACK, topic_id=first.topic_id,
                          msg_id=first.msg_id))
    time.sleep(0.4)
    pub.send(SN.SnMessage(SN.PUBLISH, flags=SN.qos_flags(1),
                          topic_id=tid, msg_id=4, data=b"lost-ack"))
    pub.recv_until(SN.PUBACK)
    d1 = sub.recv_until(SN.PUBLISH)
    assert d1.msg_id >= 32768        # native pid space: fast-path served
    assert not (d1.flags & SN.F_DUP)
    # no ack: the retransmit scan must resend the SAME msg id with DUP
    d2 = sub.recv_until(SN.PUBLISH, timeout=4.0)
    assert d2.msg_id == d1.msg_id and d2.data == b"lost-ack"
    assert d2.flags & SN.F_DUP
    # ack now: no further copies
    sub.send(SN.SnMessage(SN.PUBACK, topic_id=d2.topic_id,
                          msg_id=d2.msg_id))
    time.sleep(1.6)
    sub.sock.settimeout(0.3)
    leftover = [m for m in sub.inbox if m.type == SN.PUBLISH]
    try:
        while True:
            data = sub.sock.recv(65536)
            leftover += [m for m in sub.f.parse(data, None)[0]
                         if m.type == SN.PUBLISH]
    except socket.timeout:
        pass
    assert leftover == []
    pub.close()
    sub.close()


def test_qos2_exchange_over_sn(server, app):
    """SN qos2 publish runs the full PUBREC/PUBREL/PUBCOMP exchange
    (the oracle's fixed method-B shape) and publishes exactly once."""
    seen = []
    app.hooks.add("message.publish",
                  lambda m: seen.append(m.payload) or None, priority=-500)
    c = SnSock(server.sn_port)
    c.connect("sn-q2")
    c.send(SN.SnMessage(SN.REGISTER, msg_id=1, topic_name="q2/t"))
    tid = c.recv().topic_id
    c.send(SN.SnMessage(SN.PUBLISH, flags=SN.qos_flags(2),
                        topic_id=tid, msg_id=7, data=b"exactly"))
    rec = c.recv_until(SN.PUBREC)
    assert rec.msg_id == 7
    c.send(SN.SnMessage(SN.PUBREL, msg_id=7))
    comp = c.recv_until(SN.PUBCOMP)
    assert comp.msg_id == 7
    deadline = time.time() + 3
    while time.time() < deadline and b"exactly" not in seen:
        time.sleep(0.02)
    assert seen.count(b"exactly") == 1
    c.close()


def test_sleep_mode_buffers_until_pingreq(server):
    sub = SnSock(server.sn_port)
    pub = SnSock(server.sn_port)
    sub.connect("sn-sleeper")
    pub.connect("sn-waker")
    sub.send(SN.SnMessage(SN.SUBSCRIBE, flags=SN.qos_flags(0), msg_id=1,
                          topic_name="zz/t"))
    sub.recv_until(SN.SUBACK)
    pub.send(SN.SnMessage(SN.REGISTER, msg_id=1, topic_name="zz/t"))
    tid = pub.recv().topic_id
    # enter sleep (duration announces the silence window)
    sub.send(SN.SnMessage(SN.DISCONNECT, duration=60))
    d = sub.recv()
    assert d.type == SN.DISCONNECT
    pub.send(SN.SnMessage(SN.PUBLISH, flags=SN.qos_flags(0),
                          topic_id=tid, data=b"zzz-1"))
    pub.send(SN.SnMessage(SN.PUBLISH, flags=SN.qos_flags(0),
                          topic_id=tid, data=b"zzz-2"))
    time.sleep(0.5)
    sub.sock.settimeout(0.3)
    with pytest.raises(socket.timeout):
        sub.sock.recv(65536)          # parked, not delivered
    assert server.fast_stats()["sn_sleep_parked"] >= 2
    # the wake ping flushes parked deliveries, THEN answers PINGRESP
    sub.send(SN.SnMessage(SN.PINGREQ, clientid="sn-sleeper"))
    kinds = []
    deadline = time.time() + 5
    while time.time() < deadline:
        m = sub.recv(timeout=deadline - time.time())
        kinds.append((m.type, m.data))
        if m.type == SN.PINGRESP:
            break
    types = [k for k, _ in kinds]
    assert types[-1] == SN.PINGRESP
    pubs = [d for k, d in kinds if k == SN.PUBLISH]
    assert pubs == [b"zzz-1", b"zzz-2"]
    assert types.index(SN.PINGRESP) > types.index(SN.PUBLISH)
    pub.close()
    sub.close()


def test_disconnect_releases_session(server, app):
    c = SnSock(server.sn_port)
    c.connect("sn-bye")
    deadline = time.time() + 5
    while time.time() < deadline and \
            app.cm.lookup_channel("sn-bye") is None:
        time.sleep(0.02)
    assert app.cm.lookup_channel("sn-bye") is not None
    c.send(SN.SnMessage(SN.DISCONNECT))
    d = c.recv()
    assert d.type == SN.DISCONNECT
    while time.time() < deadline and \
            app.cm.lookup_channel("sn-bye") is not None:
        time.sleep(0.02)
    assert app.cm.lookup_channel("sn-bye") is None
    c.close()


# ---------------------------------------------------------------------------
# retained delivery on the native plane (SN/TCP/WS parity vs the oracle)
# ---------------------------------------------------------------------------

def _retain_seed(app) -> None:
    for topic, payload, qos in (
            ("v/d/temp", b"t", 1), ("v/d/hum", b"h", 0),
            ("v/other/x", b"o", 0), ("w/d/y", b"w", 2)):
        app.retainer.store(Message(topic=topic, payload=payload, qos=qos,
                                   flags={"retain": True}))


def _oracle_set(app, filt: str) -> set:
    return {(m.topic, m.payload) for m in app.retainer.match(filt)}


def test_retained_parity_tcp_ws_sn(server, app):
    """One retained store, three transports: the delivered
    (topic, payload, retain) sets must be identical to the Python
    retainer oracle on every plane — resolved below the GIL."""
    _retain_seed(app)
    time.sleep(0.3)
    base = server.fast_stats()["retain_msgs_out"]
    oracle = _oracle_set(app, "v/d/+")
    assert len(oracle) == 2

    # -- TCP ---------------------------------------------------------------
    from emqx_tpu.mqtt.frame import Parser
    s = socket.create_connection(("127.0.0.1", server.port))
    s.settimeout(5)
    p = Parser()
    vh = b"\x00\x04MQTT\x04\x02\x00\x3c" + struct.pack(">H", 3) + b"rt1"
    s.sendall(bytes([0x10, len(vh)]) + vh)
    pkts = []
    while not pkts:
        pkts += p.feed(s.recv(65536))
    body = struct.pack(">H", 1) + struct.pack(">H", 5) + b"v/d/+" + b"\x01"
    s.sendall(bytes([0x82, len(body)]) + body)
    got = set()
    deadline = time.time() + 5
    while len(got) < 2 and time.time() < deadline:
        for pkt in p.feed(s.recv(65536)):
            if getattr(pkt, "topic", None):
                assert pkt.retain is True
                got.add((pkt.topic, pkt.payload))
    assert got == oracle
    s.close()

    # -- WS (the round-7 plane rides the same retained snapshot) -----------
    from test_native_ws import NativeWsClient
    from emqx_tpu.mqtt import packet as P
    ws = NativeWsClient(server.ws_port)
    ws.handshake()
    ws.mqtt_connect("rt2")
    ws.send_mqtt(P.Subscribe(packet_id=1,
                             topic_filters=[("v/d/+", {"qos": 0})]))
    got = set()
    deadline = time.time() + 5
    while len(got) < 2 and time.time() < deadline:
        pkt = ws.recv_mqtt(timeout=deadline - time.time())
        if getattr(pkt, "topic", None):
            assert pkt.retain is True
            got.add((pkt.topic, pkt.payload))
    assert got == oracle
    ws.close()

    # -- SN ----------------------------------------------------------------
    c = SnSock(server.sn_port)
    c.connect("rt3")
    c.send(SN.SnMessage(SN.SUBSCRIBE, flags=SN.qos_flags(1), msg_id=1,
                        topic_name="v/d/+"))
    got = set()
    names = {}
    deadline = time.time() + 5
    while len(got) < 2 and time.time() < deadline:
        m = c.recv(timeout=deadline - time.time())
        if m.type == SN.REGISTER:
            names[m.topic_id] = m.topic_name
        elif m.type == SN.PUBLISH:
            assert m.flags & SN.F_RETAIN
            got.add((names[m.topic_id], m.data))
            if SN.qos_of(m.flags) > 0:
                c.send(SN.SnMessage(SN.PUBACK, topic_id=m.topic_id,
                                    msg_id=m.msg_id))
    assert got == oracle
    c.close()

    assert server.fast_stats()["retain_msgs_out"] - base >= 6


def test_retained_expiry_and_delete_mirror(server, app):
    """Deletes and expiry reach the snapshot: a cleared slot stops
    delivering natively, exactly like the oracle."""
    app.retainer.store(Message(topic="e/d/a", payload=b"live", qos=0,
                               flags={"retain": True}))
    app.retainer.store(Message(topic="e/d/b", payload=b"gone", qos=0,
                               flags={"retain": True}))
    app.retainer.delete("e/d/b")
    time.sleep(0.3)
    c = SnSock(server.sn_port)
    c.connect("rt-exp")
    c.send(SN.SnMessage(SN.SUBSCRIBE, flags=SN.qos_flags(0), msg_id=1,
                        topic_name="e/d/+"))
    got = []
    deadline = time.time() + 3
    while time.time() < deadline:
        try:
            m = c.recv(timeout=0.5)
        except socket.timeout:
            break
        if m.type == SN.PUBLISH:
            got.append(m.data)
    assert got == [b"live"]
    c.close()


def test_retained_props_fall_back_to_python(server, app):
    """A retained message with v5 properties cannot ride the native
    encode: the WHOLE seam degrades to the Python lookup (never a
    partial set) and delivery still happens."""
    app.retainer.store(Message(
        topic="p/d/a", payload=b"plain", qos=0, flags={"retain": True}))
    app.retainer.store(Message(
        topic="p/d/b", payload=b"propd", qos=0, flags={"retain": True},
        headers={"properties": {"Content-Type": "x"}}))
    time.sleep(0.3)
    assert server._retain_unmirrorable
    base = server.fast_stats()["retain_deliver"]
    from emqx_tpu.mqtt.frame import Parser
    s = socket.create_connection(("127.0.0.1", server.port))
    s.settimeout(5)
    p = Parser()
    vh = b"\x00\x04MQTT\x04\x02\x00\x3c" + struct.pack(">H", 3) + b"rp1"
    s.sendall(bytes([0x10, len(vh)]) + vh)
    pkts = []
    while not pkts:
        pkts += p.feed(s.recv(65536))
    body = struct.pack(">H", 1) + struct.pack(">H", 5) + b"p/d/+" + b"\x00"
    s.sendall(bytes([0x82, len(body)]) + body)
    got = set()
    deadline = time.time() + 5
    while len(got) < 2 and time.time() < deadline:
        for pkt in p.feed(s.recv(65536)):
            if getattr(pkt, "topic", None):
                got.add((pkt.topic, pkt.payload))
    assert got == _oracle_set(app, "p/d/+") == {
        ("p/d/a", b"plain"), ("p/d/b", b"propd")}
    # the native seam stayed OUT of it
    assert server.fast_stats()["retain_deliver"] == base
    s.close()


# ---------------------------------------------------------------------------
# degradation ladder: the asyncio gateway still serves when sn_port off
# ---------------------------------------------------------------------------

def test_asyncio_gateway_fallback(app):
    """NativeBrokerServer without sn_port + the asyncio MqttsnGateway
    on the same app: SN clients land on the Python plane, TCP clients
    on the native plane, one broker serves both."""
    import asyncio

    from emqx_tpu.broker.native_server import NativeBrokerServer

    srv = NativeBrokerServer(port=0, app=app)
    srv.start()
    try:
        assert srv.sn_port is None
        result = {}

        async def main():
            gw = app.gateway.load(SN.MqttsnGateway(port=0))
            await gw.start_listeners()
            loop = asyncio.get_running_loop()
            f = SN.Frame()
            q: asyncio.Queue = asyncio.Queue()

            class Proto(asyncio.DatagramProtocol):
                def datagram_received(self, data, addr):
                    for m in f.parse(data, None)[0]:
                        q.put_nowait(m)

            tr, _ = await loop.create_datagram_endpoint(
                Proto, remote_addr=("127.0.0.1", gw.port))
            tr.sendto(f.serialize(SN.SnMessage(
                SN.CONNECT, clientid="fb-dev")))
            ack = await asyncio.wait_for(q.get(), 5)
            result["rc"] = ack.rc
            tr.close()
            await gw.stop_listeners()
            app.gateway.gateways.pop("mqttsn", None)
            app.gateway.contexts.pop("mqttsn", None)

        asyncio.run(main())
        assert result["rc"] == SN.RC_ACCEPTED
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# review-hardening regressions
# ---------------------------------------------------------------------------

def test_sleep_does_not_burn_qos1_retries(server):
    """A qos1 delivery parked during announced sleep must neither tick
    its retry clock nor its abandonment counter while the radio is off:
    after a sleep LONGER than kSnMaxRetries * kSnRetryMs (3s) the wake
    flush is the FIRST transmission, and an unacked copy still
    retransmits with DUP afterwards (regression: the rexmit scan used
    to burn all tries during sleep, silently abandoning the delivery
    and counting drops_inflight for messages that were never sent)."""
    pub = SnSock(server.sn_port)
    sub = SnSock(server.sn_port)
    pub.connect("sn-slrx-p")
    sub.connect("sn-slrx-s")
    pub.send(SN.SnMessage(SN.REGISTER, msg_id=1, topic_name="slrx/t"))
    tid = pub.recv().topic_id
    sub.send(SN.SnMessage(SN.SUBSCRIBE, flags=SN.qos_flags(1), msg_id=2,
                          topic_name="slrx/t"))
    sub.recv_until(SN.SUBACK)
    # warm the permit so the parked delivery is native-plane tracked
    pub.send(SN.SnMessage(SN.PUBLISH, flags=SN.qos_flags(1),
                          topic_id=tid, msg_id=3, data=b"warm"))
    pub.recv_until(SN.PUBACK)
    w = sub.recv_until(SN.PUBLISH)
    sub.send(SN.SnMessage(SN.PUBACK, topic_id=w.topic_id,
                          msg_id=w.msg_id))
    time.sleep(0.4)
    drops_before = server.fast_stats()["drops_inflight"]
    sub.send(SN.SnMessage(SN.DISCONNECT, duration=60))
    assert sub.recv().type == SN.DISCONNECT
    pub.send(SN.SnMessage(SN.PUBLISH, flags=SN.qos_flags(1),
                          topic_id=tid, msg_id=4, data=b"parked"))
    pub.recv_until(SN.PUBACK)
    time.sleep(3.6)                   # > kSnMaxRetries * kSnRetryMs
    assert server.fast_stats()["drops_inflight"] == drops_before
    sub.send(SN.SnMessage(SN.PINGREQ, clientid="sn-slrx-s"))
    d1 = sub.recv_until(SN.PUBLISH)
    assert d1.data == b"parked"
    # no ack: the retry clock restarted at wake, so a DUP copy follows
    d2 = sub.recv_until(SN.PUBLISH, timeout=4.0)
    assert d2.msg_id == d1.msg_id and (d2.flags & SN.F_DUP)
    sub.send(SN.SnMessage(SN.PUBACK, topic_id=d2.topic_id,
                          msg_id=d2.msg_id))
    pub.close()
    sub.close()


def test_reconnect_same_clientid_reruns_session(server):
    """A CONNECT on a live conn with the SAME clientid re-runs the
    session open (oracle parity: auth + open_session run on EVERY
    CONNECT) instead of being waved through as a CONNACK retransmit —
    a rebooted F_CLEAN device must get a fresh topic-id registry, not
    the ghost of its old one."""
    c = SnSock(server.sn_port)
    c.connect("sn-reboot")
    c.send(SN.SnMessage(SN.REGISTER, msg_id=1, topic_name="rb/t"))
    old_tid = c.recv().topic_id
    assert old_tid > 0
    # the device reboots: same addr, same clientid, clean start
    c.connect("sn-reboot")
    # the old registry must be gone — a qos1 PUBLISH on the stale id
    # answers INVALID_TOPIC_ID, the client's cue to re-REGISTER
    c.send(SN.SnMessage(SN.PUBLISH, flags=SN.qos_flags(1),
                        topic_id=old_tid, msg_id=2, data=b"stale"))
    pa = c.recv_until(SN.PUBACK)
    assert pa.rc == SN.RC_INVALID_TOPIC_ID
    c.send(SN.SnMessage(SN.REGISTER, msg_id=3, topic_name="rb/t"))
    assert c.recv_until(SN.REGACK).rc == SN.RC_ACCEPTED
    c.close()


def test_pipelined_connect_served_not_bounced(server):
    """Messages pipelined behind CONNECT — even packed into the SAME
    datagram — are parked through the CONNECT->CONNACK round trip and
    then served in order (the oracle connects synchronously, so the
    identical byte sequence succeeds there; the native plane used to
    bounce each one with DISCONNECT)."""
    sub = SnSock(server.sn_port)
    sub.connect("sn-pipe-s")
    sub.send(SN.SnMessage(SN.SUBSCRIBE, flags=SN.qos_flags(1), msg_id=1,
                          topic_name="pre/defined"))
    sub.recv_until(SN.SUBACK)
    c = SnSock(server.sn_port)
    f = c.f
    dgram = (f.serialize(SN.SnMessage(SN.CONNECT, flags=SN.F_CLEAN,
                                      duration=60, clientid="sn-pipe"))
             + f.serialize(SN.SnMessage(SN.REGISTER, msg_id=2,
                                        topic_name="pipe/r"))
             + f.serialize(SN.SnMessage(
                 SN.PUBLISH, flags=SN.qos_flags(1) | SN.TID_PREDEF,
                 topic_id=1, msg_id=3, data=b"piped")))
    c.sock.send(dgram)
    got = {}
    deadline = time.time() + 5
    while len(got) < 3 and time.time() < deadline:
        m = c.recv(timeout=max(0.1, deadline - time.time()))
        assert m.type != SN.DISCONNECT, "pipelined message was bounced"
        got.setdefault(m.type, m)
    assert got[SN.CONNACK].rc == SN.RC_ACCEPTED
    assert got[SN.REGACK].rc == SN.RC_ACCEPTED
    assert got[SN.PUBACK].rc == SN.RC_ACCEPTED
    assert sub.recv_until(SN.PUBLISH).data == b"piped"
    sub.close()
    c.close()


def test_retainer_mirror_attach_is_atomic_replay(app):
    """mirror_attach replays the existing store through the callback
    and registers it under ONE lock hold — the boot snapshot and the
    observer stream are a single ordered event sequence, so a store or
    delete racing server boot can never fall in a gap."""
    app.retainer.store(Message(topic="ma/a", payload=b"1", qos=0,
                               flags={"retain": True}))
    events = []
    app.retainer.mirror_attach(
        lambda op, t, m, dl: events.append((op, t)))
    assert events == [("set", "ma/a")]
    app.retainer.store(Message(topic="ma/b", payload=b"2", qos=0,
                               flags={"retain": True}))
    app.retainer.delete("ma/a")
    assert events == [("set", "ma/a"), ("set", "ma/b"), ("del", "ma/a")]


def test_oversized_delivery_drops_not_truncates(server):
    """A publish whose payload cannot fit the SN u16 wire length must
    be DROPPED at the translation seam (sn_drops_oversize), never
    length-truncated — a truncated length field would make the egress
    carve misparse payload bytes as message boundaries and corrupt
    every queued datagram behind it. Deliveries after the drop still
    flow."""
    sub = SnSock(server.sn_port)
    sub.connect("sn-big-s")
    sub.send(SN.SnMessage(SN.SUBSCRIBE, flags=SN.qos_flags(0), msg_id=1,
                          topic_name="big/t"))
    sub.recv_until(SN.SUBACK)

    import asyncio
    from emqx_tpu.mqtt.client import MqttClient

    async def blast():
        c = MqttClient(port=server.port, clientid="big-pub")
        await c.connect()
        await c.publish("big/t", b"x" * 70_000)      # > 65526: dropped
        await c.publish("big/t", b"fits")            # must still arrive
        await c.close()
    asyncio.run(blast())
    d = sub.recv_until(SN.PUBLISH)
    assert d.data == b"fits"
    assert server.fast_stats()["sn_drops_oversize"] >= 1
    sub.close()


def test_oracle_registry_full_parity():
    """Both planes refuse the reserved id 0: a full NORMAL registry
    answers REGACK rc=congestion, and a delivery needing an id it
    cannot mint is dropped (not emitted with topic_id=0)."""
    ch = SN.Channel.__new__(SN.Channel)
    ch.registry = SN.Registry()
    ch.id_of_topic = {}
    ch.topic_of_id = {t: f"t/{t}" for t in range(1, 0x10000)}
    ch._next_tid = 0
    ch._next_mid = 0
    ch.conn_state = "connected"
    ch.awake = True
    ch._sleep_buffer = []
    ch.max_sleep_buffer = 10

    out = ch.handle_in(SN.SnMessage(SN.REGISTER, msg_id=1,
                                    topic_name="nope/t"))
    assert out[0].type == SN.REGACK and out[0].rc == SN.RC_CONGESTION
    assert out[0].topic_id == 0

    class _Msg:
        topic = "nope/t"
        payload = b"p"
        qos = 0

    class _Ctx:
        @staticmethod
        def unmount(t):
            return t
    ch.ctx = _Ctx()
    assert ch.handle_deliver([("nope/t", _Msg())]) == []

    # oversized payloads drop on the oracle exactly like the native seam
    class _Big(_Msg):
        payload = b"x" * (SN.MAX_PAYLOAD + 1)
    assert ch.handle_deliver([("nope/t", _Big())]) == []
