"""Conn-scale plane (round 16): timer wheel, parked-conn hibernation,
accept-storm shedding.

The reference broker's headline is 100M+ connections per cluster; it
gets there by hibernating idle connection processes and waking them on
traffic. Our analogue (native/src/wheel.h + park.h + host.cc):

- a hierarchical timer wheel per shard replaces every per-cycle O(N)
  deadline sweep (keepalive, SN qos1 retransmit, trunk ack watchdog)
  with O(expired) cascades — pinned here against a brute-force oracle;
- idle conns hibernate into a slab-allocated parked record a couple
  hundred bytes wide (the 20KB ack-bitmap AckState collapses to a
  sparse summary) and re-inflate on the FIRST BYTE via the epoll
  wakeup, before any fast-path work — a mid-flight qos1 window
  survives the round trip intact;
- keepalive PINGREQs are answered from the parked record without
  inflation, so an idle-but-pinging herd stays hibernated;
- accept storms hit a governor rung BEFORE any conn side effect:
  backlog pressure defers to the kernel backlog, a memory-budget
  breach sheds close-with-ledger (messages.ledger.accept_shed).
"""

import socket
import struct
import time

import pytest

from emqx_tpu import native

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native lib unavailable")

from emqx_tpu.app import BrokerApp            # noqa: E402
from emqx_tpu.broker.native_server import NativeBrokerServer  # noqa: E402

CONNECT_VH = b"\x00\x04MQTT\x04\x02\x00\x3c"


def _connect(port, cid: bytes):
    s = socket.create_connection(("127.0.0.1", port))
    vh = CONNECT_VH + struct.pack(">H", len(cid)) + cid
    s.sendall(bytes([0x10, len(vh)]) + vh)
    return s


def _pub_frame(topic: bytes, payload: bytes, qos=0, pid=0):
    vh = struct.pack(">H", len(topic)) + topic
    if qos:
        vh += struct.pack(">H", pid)
    body = vh + payload
    return bytes([0x30 | (qos << 1)]) + bytes([len(body)]) + body


def _pump(host, events=None, ms=20):
    for kind, cid, payload in host.poll(ms):
        if events is not None:
            events.append((kind, cid, payload))


def _pump_until(host, cond, timeout=5.0, events=None):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        _pump(host, events)
        if cond():
            return True
    return False


def _open_fast_conn(host, port, cid: bytes, keepalive_ms=60000):
    """Connect a raw socket, CONNACK it, enable the fast path + native
    keepalive. Returns (socket, conn_id)."""
    s = _connect(port, cid)
    got = {}

    def see():
        for kind, c, payload in host.poll(20):
            if kind == native.EV_OPEN:
                got["open"] = c
            elif kind == native.EV_FRAME:
                got["frame"] = True
        return "open" in got and "frame" in got

    t0 = time.monotonic()
    while time.monotonic() - t0 < 5 and not see():
        pass
    assert "frame" in got, "CONNECT never surfaced"
    conn = got["open"]
    host.send(conn, b"\x20\x02\x00\x00")
    _pump(host)
    s.settimeout(5)
    assert s.recv(4) == b"\x20\x02\x00\x00"
    host.enable_fast(conn, 4)
    if keepalive_ms:
        host.set_keepalive(conn, keepalive_ms)
    _pump(host)
    return s, conn


# -- the wheel vs a brute-force oracle ---------------------------------------


@pytest.mark.parametrize("seed", [7, 1234, 0xDEADBEEF])
def test_wheel_matches_brute_force_oracle(seed):
    """10k+ timers through a seeded arm/cancel/advance script: every
    Advance's fired set must equal the brute-force oracle's EXACTLY —
    {armed keys whose deadline, rounded up to the 16ms tick, passed the
    advance clock's tick}. This pins never-early (a deadline fires only
    once its tick is reached), never-lost (the final drain flushes
    everything), and cascade correctness across all wheel levels (the
    script jumps up to 30s per advance, crossing level-1/2 windows)."""
    events = native.wheel_selftest(seed, 30000)
    armed: dict = {}
    arms = cancels = fired_total = 0
    max_live = 0
    for rec in events:
        if rec[0] == "arm":
            armed[rec[1]] = rec[2]
            arms += 1
            max_live = max(max_live, len(armed))
        elif rec[0] == "cancel":
            assert rec[1] in armed, "script cancelled a dead timer"
            del armed[rec[1]]
            cancels += 1
        else:
            _, now, fired = rec
            cur_tick = now >> 4
            due = {k for k, d in armed.items()
                   if ((d + 15) >> 4) <= cur_tick}
            got = set(fired)
            assert got == due, (
                f"advance to {now}: missing {sorted(due - got)[:5]} "
                f"extra {sorted(got - due)[:5]}")
            assert len(fired) == len(got), "duplicate fire in one batch"
            for k in fired:
                del armed[k]
            fired_total += len(fired)
    assert arms >= 10000, arms          # the 10k-timer bar
    assert fired_total == arms - cancels
    assert not armed, "final drain left timers armed"


# -- hibernation: park -> first byte -> inflate ------------------------------


def test_park_first_byte_reinflate_qos1_window_intact():
    """A subscriber with a MID-FLIGHT qos1 delivery (unacked pid in the
    native window) hibernates; its PUBACK — the first byte after the
    park — re-inflates the conn and lands on the right window slot,
    and the pid allocator resumes where it left off."""
    host = native.NativeHost(port=0, max_size=1 << 16)
    host.set_park(True, park_after_ms=250)
    sub_s, sub = _open_fast_conn(host, host.port, b"pksub")
    pub_s, pub = _open_fast_conn(host, host.port, b"pkpub")
    host.sub_add(sub, "pk/w", qos=1)
    host.permit(pub, "pk/w")
    _pump(host)
    pub_s.sendall(_pub_frame(b"pk/w", b"m0", qos=1, pid=7))
    # subscriber receives the delivery with a NATIVE pid (>= 32768)
    buf = b""
    t0 = time.monotonic()
    while len(buf) < 12 and time.monotonic() - t0 < 5:
        _pump(host)
        try:
            sub_s.settimeout(0.1)
            buf += sub_s.recv(64)
        except socket.timeout:
            pass
    assert buf[:1] == b"\x32", buf      # qos1 PUBLISH
    pid1 = struct.unpack(">H", buf[8:10])[0]
    assert pid1 == 32768
    st = host.stats()
    assert st["fast_in"] == 1 and st["qos1_in"] == 1
    # both conns idle past the park horizon WITH the window open
    assert _pump_until(host,
                       lambda: host.conn_counts()["parked"] == 2, 5)
    # the first byte: the unacked delivery's PUBACK
    sub_s.sendall(b"\x40\x02" + struct.pack(">H", pid1))
    assert _pump_until(host,
                       lambda: host.stats()["native_acks"] == 1, 5)
    cc = host.conn_counts()
    assert cc["resident"] >= 1          # the subscriber woke
    assert host.stats()["conns_inflated"] >= 1
    # window intact: the next delivery continues the pid sequence
    host.permit(pub, "pk/w")            # permits re-earn after a park
    _pump(host)
    pub_s.sendall(_pub_frame(b"pk/w", b"m1", qos=1, pid=8))
    buf2 = b""
    t0 = time.monotonic()
    while len(buf2) < 12 and time.monotonic() - t0 < 5:
        _pump(host)
        try:
            sub_s.settimeout(0.1)
            buf2 += sub_s.recv(64)
        except socket.timeout:
            pass
    pid2 = struct.unpack(">H", buf2[8:10])[0]
    assert pid2 == 32769, "pid allocator lost its place across the park"
    host.destroy()


def test_parked_ping_answers_without_inflation():
    """Keepalive PINGREQs on a hibernating conn are answered from the
    parked record: the herd stays parked through its keepalive
    schedule (parked_pings counts them; conns_inflated stays 0)."""
    host = native.NativeHost(port=0, max_size=1 << 16)
    host.set_park(True, park_after_ms=200)
    s, conn = _open_fast_conn(host, host.port, b"pping")
    assert _pump_until(host,
                       lambda: host.conn_counts()["parked"] == 1, 5)
    for _ in range(3):
        s.sendall(b"\xc0\x00")
        got = b""
        t0 = time.monotonic()
        while len(got) < 2 and time.monotonic() - t0 < 3:
            _pump(host)
            try:
                s.settimeout(0.1)
                got += s.recv(2 - len(got))
            except socket.timeout:
                pass
        assert got == b"\xd0\x00"
    st = host.stats()
    cc = host.conn_counts()
    assert cc["parked"] == 1, "a ping inflated the conn"
    assert st["parked_pings"] == 3
    assert st["conns_inflated"] == 0
    host.destroy()


def test_delivery_to_parked_conn_inflates():
    """A publish matching a hibernating subscriber re-inflates it on
    the delivery path (FindConnInflate) — hibernation is invisible to
    the fan-out contract."""
    host = native.NativeHost(port=0, max_size=1 << 16)
    host.set_park(True, park_after_ms=200)
    sub_s, sub = _open_fast_conn(host, host.port, b"dsub")
    host.sub_add(sub, "d/t", qos=0)
    _pump(host)
    assert _pump_until(host,
                       lambda: host.conn_counts()["parked"] == 1, 5)
    pub_s, pub = _open_fast_conn(host, host.port, b"dpub")
    host.permit(pub, "d/t")
    _pump(host)
    pub_s.sendall(_pub_frame(b"d/t", b"hello"))
    got = b""
    t0 = time.monotonic()
    while len(got) < 12 and time.monotonic() - t0 < 5:
        _pump(host)
        try:
            sub_s.settimeout(0.1)
            got += sub_s.recv(64)
        except socket.timeout:
            pass
    assert b"hello" in got
    assert host.stats()["conns_inflated"] >= 1
    host.destroy()


# -- keepalive on the wheel --------------------------------------------------


def test_keepalive_wheel_closes_idle_and_honors_traffic():
    """The wheel's keepalive fire closes a silent conn with the same
    "keepalive_timeout" reason the Python sweep used — and a conn that
    keeps pinging (even while PARKED) never trips it."""
    host = native.NativeHost(port=0, max_size=1 << 16)
    host.set_park(True, park_after_ms=150)
    quiet_s, quiet = _open_fast_conn(host, host.port, b"kaq",
                                     keepalive_ms=400)
    live_s, live = _open_fast_conn(host, host.port, b"kal",
                                   keepalive_ms=400)
    closed = []

    def see():
        for kind, c, payload in host.poll(20):
            if kind == native.EV_CLOSED:
                closed.append((c, payload))
        return any(c == quiet for c, _ in closed)

    t0 = time.monotonic()
    while time.monotonic() - t0 < 3.0:
        if see():
            break
        if time.monotonic() - t0 < 2.5:
            try:
                live_s.sendall(b"\xc0\x00")   # live conn keeps pinging
            except OSError:
                pass
        time.sleep(0.05)
    reasons = {c: p for c, p in closed}
    assert quiet in reasons, "idle conn never timed out on the wheel"
    assert reasons[quiet] == b"keepalive_timeout"
    assert live not in reasons, "pinging conn was killed"
    host.destroy()


# -- accept-storm governance -------------------------------------------------


def test_shed_ladder_order_and_ledger():
    """Memory-budget breach sheds the accept BEFORE any side effect —
    no conn id, no OPEN event — and every shed is visible as the
    conns_shed stat + a messages.ledger.accept_shed entry (kind-12)."""
    host = native.NativeHost(port=0, max_size=1 << 16)
    # budget sized for ONE resident-conn estimate: the first accept
    # fits, the second crosses the budget and sheds
    host.set_park(True, park_after_ms=0, accept_burst=0,
                  mem_budget_bytes=1500)
    events = []
    s1 = socket.create_connection(("127.0.0.1", host.port))
    assert _pump_until(
        host, lambda: any(e[0] == native.EV_OPEN for e in events), 5,
        events=events)
    s2 = socket.create_connection(("127.0.0.1", host.port))
    ledger = []

    def cond():
        return host.stats()["conns_shed"] >= 1 and ledger

    t0 = time.monotonic()
    while time.monotonic() - t0 < 5 and not cond():
        for kind, cid, payload in host.poll(20):
            events.append((kind, cid, payload))
            if kind == native.EV_SPANS:
                ledger += [r for r in native.parse_spans(payload)
                           if r[0] == "ledger"]
    assert host.stats()["conns_shed"] >= 1
    opens = [e for e in events if e[0] == native.EV_OPEN]
    assert len(opens) == 1, (
        "a shed accept leaked an OPEN event — side effect before admit")
    want = native.LEDGER_REASONS.index("accept_shed") + 1
    assert any(r[1] == want for r in ledger), ledger
    # the shed socket is really dead (closed, not silently parked)
    s2.settimeout(3)
    assert s2.recv(16) == b""
    s1.close()
    host.destroy()


def test_accept_burst_defers_without_shedding():
    """Backlog pressure (the per-cycle accept burst cap) DEFERS: every
    conn still connects — across later cycles — and nothing sheds."""
    host = native.NativeHost(port=0, max_size=1 << 16)
    host.set_park(True, park_after_ms=0, accept_burst=2)
    socks = [socket.create_connection(("127.0.0.1", host.port))
             for _ in range(9)]
    opens = []
    t0 = time.monotonic()
    while time.monotonic() - t0 < 5 and len(opens) < 9:
        for kind, cid, payload in host.poll(20):
            if kind == native.EV_OPEN:
                opens.append(cid)
    assert len(opens) == 9, "deferred accepts were lost"
    assert host.stats()["conns_shed"] == 0
    for s in socks:
        s.close()
    host.destroy()


# -- the memory diet ---------------------------------------------------------


def test_parked_record_memory_bound():
    """The parked record stays inside its diet: a few hundred bytes per
    conn INCLUDING the subscription bookkeeping — against the ~20KB a
    resident conn's AckState alone could hold."""
    host = native.NativeHost(port=0, max_size=1 << 16)
    host.set_park(True, park_after_ms=100)
    host.synth_conns(1000, keepalive_ms=600000, sub_every=1,
                     topic_prefix="diet")
    assert _pump_until(
        host, lambda: host.conn_counts()["parked"] >= 1000, 10)
    cc = host.conn_counts()
    per_conn = cc["parked_bytes"] / cc["parked"]
    assert per_conn <= 512, f"parked record grew to {per_conn:.0f}B/conn"
    assert cc["timers_armed"] >= 1000   # keepalives stay armed, parked
    host.destroy()


def test_housekeep_cost_is_o_expired_not_o_parked():
    """50k parked conns with armed (far-future) keepalives must not
    make the idle poll cycle O(N): the wheel pays O(expired + cascade)
    per cycle, so 20 idle cycles over a 50k-parked herd complete fast
    even on the 1-core CI box (the old per-conn sweep walked every
    conn every housekeep)."""
    host = native.NativeHost(port=0, max_size=1 << 16)
    host.set_park(True, park_after_ms=100)
    for _ in range(5):
        host.synth_conns(10000, keepalive_ms=3_600_000)
    assert _pump_until(
        host, lambda: host.conn_counts()["parked"] >= 50000, 20)
    t0 = time.monotonic()
    for _ in range(20):
        list(host.poll(0))
    dt = time.monotonic() - t0
    assert dt < 2.0, f"20 idle cycles over 50k parked took {dt:.2f}s"
    host.destroy()


# -- the full server ---------------------------------------------------------


def test_server_parks_conns_and_housekeep_scan_drains():
    """End-to-end through NativeBrokerServer: a real client hibernates
    after the park horizon, publishes still reach it (inflate on
    delivery), the housekeep scan set drains to empty once sessions
    are idle (the O(N) Python sweep is gone), and the conns.* fixed
    metric slots fold the events."""
    import asyncio

    from emqx_tpu.mqtt.client import MqttClient

    server = NativeBrokerServer(port=0, app=BrokerApp(),
                                park_after_ms=300)
    server.start()

    async def main():
        sub = MqttClient(port=server.port, clientid="scale-sub")
        await sub.connect()
        await sub.subscribe("sc/t", qos=0)
        pub = MqttClient(port=server.port, clientid="scale-pub")
        await pub.connect()
        await pub.publish("sc/t", b"before", qos=0)
        m = await sub.recv(timeout=5)
        assert m.payload == b"before"
        # idle past the horizon: both conns hibernate
        t0 = time.monotonic()
        while time.monotonic() - t0 < 5:
            if server.fast_stats()["conns_parked"] >= 2:
                break
            await asyncio.sleep(0.1)
        assert server.fast_stats()["conns_parked"] >= 2
        # the housekeep scan set drained (sessions hold no timer work)
        server._housekeep_conns(0)
        with server._scan_lock:
            assert not server._scan_conns, list(server._scan_conns)
        # a publish wakes the publisher AND the parked subscriber
        await pub.publish("sc/t", b"after", qos=0)
        m = await sub.recv(timeout=5)
        assert m.payload == b"after"
        assert server.fast_stats()["conns_inflated"] >= 1
        # the fixed metric slots fold the events (render-at-zero is
        # pinned in test_stats_lint; here they must count)
        server._merge_fast_metrics()
        assert server.broker.metrics.val("conns.parked") >= 2
        assert server.broker.metrics.val("conns.inflated") >= 1
        await sub.close()
        await pub.close()

    asyncio.run(main())
    server.stop()


def test_server_native_keepalive_closes_dead_conn():
    """A conn that negotiates keepalive=1 and goes silent is closed by
    the C++ wheel (no Python sweep involved) and reaped from the
    server's conn table."""
    server = NativeBrokerServer(port=0, app=BrokerApp())
    server.start()
    s = socket.create_connection(("127.0.0.1", server.port))
    vh = b"\x00\x04MQTT\x04\x02\x00\x01" + struct.pack(">H", 4) + b"dead"
    s.sendall(bytes([0x10, len(vh)]) + vh)
    s.settimeout(5)
    assert s.recv(4)[:1] == b"\x20"     # CONNACK
    # keepalive 1s -> native deadline 1500ms; the socket must die
    t0 = time.monotonic()
    dead = False
    while time.monotonic() - t0 < 6:
        try:
            if s.recv(16) == b"":
                dead = True
                break
        except socket.timeout:
            break
    assert dead, "idle conn outlived its keepalive on the wheel"
    t0 = time.monotonic()
    while time.monotonic() - t0 < 3 and server.conns:
        time.sleep(0.1)
    assert not server.conns
    server.stop()


# -- the storm soak (slow) ---------------------------------------------------


@pytest.mark.slow
def test_connscale_200k_storm_soak():
    """200k-conn storm at CI scale (the bench drives 1M on the box):
    a synthetic herd floods in through the real admission + park
    machinery, hibernates whole, survives an inflate/re-park churn
    wave, and tears down clean — with the parked-record memory bound
    holding at scale."""
    host = native.NativeHost(port=0, max_size=1 << 16)
    host.set_park(True, park_after_ms=150)
    n = 200_000
    for _ in range(20):
        host.synth_conns(n // 20, keepalive_ms=3_600_000, sub_every=10,
                         topic_prefix="soak")
        _pump(host, ms=0)
    assert _pump_until(
        host, lambda: host.conn_counts()["parked"] >= n, 60)
    cc = host.conn_counts()
    assert cc["parked_bytes"] / cc["parked"] <= 512
    # churn wave: cross-thread sends inflate a sample of the herd
    # (synthetic egress is discarded; the park machinery is real)
    sample = range(1, n, 997)
    for cid in sample:
        host.send(cid, b"\xd0\x00")
    assert _pump_until(
        host,
        lambda: host.stats()["conns_inflated"] >= len(list(sample)) // 2,
        30)
    # they re-park
    assert _pump_until(
        host, lambda: host.conn_counts()["parked"] >= n, 60)
    # teardown a slab of the herd while parked
    for cid in range(1, 5001):
        host.close_conn(cid)
    assert _pump_until(
        host,
        lambda: host.conn_counts()["parked"] + host.conn_counts()[
            "resident"] <= n - 4000, 30)
    host.destroy()
